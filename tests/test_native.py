"""Native C++ components vs pure-Python oracles."""

import os
import random

import pytest

from khipu_tpu.base.crypto.keccak import keccak256_py, keccak512_py
from khipu_tpu.native import keccak as native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no native toolchain"
)


def test_native_keccak256_vs_oracle():
    rng = random.Random(0)
    for n in [0, 1, 55, 56, 135, 136, 137, 271, 272, 273, 576, 4096]:
        data = rng.randbytes(n)
        assert native.keccak256(data) == keccak256_py(data), n


def test_native_keccak512_vs_oracle():
    rng = random.Random(1)
    for n in [0, 1, 71, 72, 73, 143, 144, 145, 576]:
        data = rng.randbytes(n)
        assert native.keccak512(data) == keccak512_py(data), n


def test_native_keccak_known_vectors():
    assert (
        native.keccak256(b"").hex()
        == "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
    )
    assert (
        native.keccak256(b"abc").hex()
        == "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
    )


def test_native_batch_matches_singles():
    rng = random.Random(2)
    msgs = [rng.randbytes(rng.randint(0, 600)) for _ in range(257)]
    assert native.keccak256_batch(msgs) == [
        native.keccak256(m) for m in msgs
    ]
    assert native.keccak256_batch([]) == []


# ------------------------------------------------- rlp resize guard

def _rlp_ext():
    from khipu_tpu.base.rlp import RLPError
    from khipu_tpu.native.build import load_rlp_ext

    ext = load_rlp_ext()
    if ext is None:
        pytest.skip("rlp extension unavailable")
    ext._set_error(RLPError)
    return ext


class TestRlpEncodeResizeGuard:
    """rlp_ext.c two-pass encode: a bytearray resized between the
    size pass and the write pass (GC finalizer / rogue thread) must
    raise RLPError — never scribble past the output buffer."""

    def test_grow_between_passes_raises(self):
        from khipu_tpu.base.rlp import RLPError

        ext = _rlp_ext()
        ba = bytearray(b"x" * 10)
        ext._set_encode_hook(lambda: ba.extend(b"y" * 90))
        try:
            with pytest.raises(RLPError):
                ext.encode([ba, b"tail"])
        finally:
            ext._set_encode_hook(None)

    def test_shrink_between_passes_raises(self):
        from khipu_tpu.base.rlp import RLPError

        ext = _rlp_ext()
        ba = bytearray(b"x" * 100)
        ext._set_encode_hook(lambda: ba.__init__(b"x" * 3))
        try:
            with pytest.raises(RLPError):
                ext.encode([ba, b"tail"])
        finally:
            ext._set_encode_hook(None)

    def test_hook_without_resize_is_benign(self):
        ext = _rlp_ext()
        ba = bytearray(b"hello rlp")
        ext._set_encode_hook(lambda: None)
        try:
            out = ext.encode([ba, b"tail"])
        finally:
            ext._set_encode_hook(None)
        assert out == ext.encode([ba, b"tail"])  # hook cleared, same bytes

    def test_nested_list_growth_raises(self):
        from khipu_tpu.base.rlp import RLPError

        ext = _rlp_ext()
        inner = bytearray(b"ab")
        ext._set_encode_hook(lambda: inner.extend(b"c" * 60))
        try:
            with pytest.raises(RLPError):
                ext.encode([[inner], [b"x", [inner]]])
        finally:
            ext._set_encode_hook(None)
