"""Cluster-wide distributed tracing (the PR-5 tentpole): Dapper-style
metadata propagation over the gRPC bridge, the GetTraceSpans span-ring
pull, the Ping clock-probe offset estimate, and the merged chrome trace
that nests shard-side server spans inside the exact driver RPC spans
that caused them — offset-corrected, non-negative nesting.

Also the per-driver tracer rings: two drivers (or two bridge servers)
in one process record into disjoint rings (the ROADMAP isolation note).
"""

import dataclasses
import json

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.observability import export
from khipu_tpu.observability.trace import (
    Tracer,
    current_tracer,
    tracer,
    use_tracer,
)
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.replay import ReplayDriver

grpc = pytest.importorskip("grpc")

from khipu_tpu.bridge import (  # noqa: E402
    CLOCK_PROBE,
    MD_PARENT_TOKEN,
    MD_SAMPLED,
    MD_TRACE_ID,
    BridgeClient,
    BridgeServer,
    _encode_trace_spans,
    decode_trace_spans,
)

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(3)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ALLOC = {a: 10**21 for a in ADDRS}


def build_blocks(n=4):
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG, GenesisSpec(alloc=ALLOC)
    )
    return [
        builder.add_block(
            [sign_transaction(
                Transaction(i, 10**9, 21000, ADDRS[1], 5), KEYS[0],
                chain_id=1,
            )],
            coinbase=b"\xaa" * 20,
        )
        for i in range(n)
    ]


def _start_shard():
    bc = Blockchain(Storages(), CFG)
    bc.load_genesis(GenesisSpec(alloc=ALLOC))
    server = BridgeServer(bc, CFG)
    port = server.start(port=0)
    server.tracer.enable()
    return server, BridgeClient(f"127.0.0.1:{port}", deadline=10.0)


@pytest.fixture()
def shard():
    server, client = _start_shard()
    yield server, client
    client.close()
    server.stop()


@pytest.fixture()
def driver_tracing():
    """Module tracer enabled with a fresh ring for the driver side."""
    tracer.enable()
    tracer.reset()
    yield tracer
    tracer.disable()
    tracer.reset()


# --------------------------------------------------------- propagation


class TestPropagation:
    def test_server_span_links_remote_parent(self, shard, driver_tracing):
        """The client's bridge.call span token + trace id arrive as
        metadata; the server records them as remote_* tags on its
        bridge.serve span — the cross-process edge the merge resolves."""
        server, client = shard
        with tracer.span("driver.work"):
            client.best_block()
        calls = [s for s in tracer.snapshot() if s.name == "bridge.call"]
        assert len(calls) == 1
        assert calls[0].tags["method"] == "BestBlock"
        serves = [
            s for s in server.tracer.snapshot()
            if s.name == "bridge.serve.BestBlock"
        ]
        assert len(serves) == 1
        tags = serves[0].tags
        assert tags["remote_trace"] == tracer.trace_id
        assert tags["remote_parent"] == calls[0].sid

    def test_unsampled_call_carries_no_remote_tags(self, shard):
        """Tracing off on the caller: the metadata keys still ship
        (khipu-sampled="") but the server must NOT record a remote
        linkage into a trace id that never recorded the client half —
        it keeps its own local, unlinked serve span."""
        server, client = shard
        assert not tracer.enabled
        client.best_block()
        serves = [
            s for s in server.tracer.snapshot()
            if s.name == "bridge.serve.BestBlock"
        ]
        assert len(serves) == 1
        assert "remote_trace" not in serves[0].tags
        assert "remote_parent" not in serves[0].tags

    def test_head_sampled_out_trace_skips_server_span(self, shard):
        """khipu-sampled="0" is a DECISION, not an absence: the caller's
        head sampler dropped this trace id, so the server records
        nothing — the trace is whole or absent fleet-wide."""
        server, client = shard
        tracer.enable()
        tracer.set_sample_rate(0)  # tracer on, every trace dropped
        try:
            assert not tracer.enabled
            client.best_block()
        finally:
            tracer.set_sample_rate(10_000)
            tracer.disable()
            tracer.reset()
        serves = [
            s for s in server.tracer.snapshot()
            if s.name == "bridge.serve.BestBlock"
        ]
        assert serves == []

    def test_metadata_keys_are_unconditional(self, shard):
        """Wire contract: all three keys ride EVERY call — sampled
        flips with tracer state, the ids stay greppable either way."""
        _, client = shard
        captured = []
        real = client.channel.unary_unary

        def wrap(path, request_serializer=None,
                 response_deserializer=None):
            fn = real(path, request_serializer=request_serializer,
                      response_deserializer=response_deserializer)

            def call(payload, timeout=None, metadata=None):
                captured.append(dict(metadata or ()))
                return fn(payload, timeout=timeout, metadata=metadata)

            return call

        client.channel.unary_unary = wrap
        client.ping(b"x")  # tracing off
        tracer.enable()
        live_trace_id = tracer.trace_id
        try:
            client.ping(b"y")
        finally:
            tracer.disable()
            tracer.reset()
        off, on = captured
        for md in (off, on):
            assert {MD_TRACE_ID, MD_PARENT_TOKEN, MD_SAMPLED} <= set(md)
        assert off[MD_SAMPLED] == ""  # off = no sampling decision
        assert off[MD_PARENT_TOKEN] == ""  # no live span when off
        assert on[MD_SAMPLED] == "1"
        assert on[MD_TRACE_ID] == live_trace_id
        assert on[MD_PARENT_TOKEN].isdigit()  # the bridge.call token


# -------------------------------------------------------- span-ring RPC


class TestGetTraceSpans:
    def test_roundtrip_preserves_fields(self):
        t = Tracer(capacity=64)
        t.enable()
        with t.span("outer", block=7, root=b"\xab\xcd"):
            with t.span("inner"):
                pass
        t.event("blip", kind="x")
        decoded = decode_trace_spans(_encode_trace_spans(t))
        assert decoded["traceId"] == t.trace_id
        spans = {s["name"]: s for s in decoded["spans"]}
        assert set(spans) == {"outer", "inner", "blip"}
        assert spans["outer"]["tags"] == {"block": 7, "root": "abcd"}
        assert spans["inner"]["parent"] == spans["outer"]["sid"]
        assert spans["outer"]["t0_wall"] <= spans["inner"]["t0_wall"]
        assert spans["inner"]["t1_wall"] <= spans["outer"]["t1_wall"]
        for s in decoded["spans"]:
            assert s["t1_wall"] >= s["t0_wall"]
            assert not s["error"]
            assert s["thread_name"]

    def test_rpc_pull_matches_server_ring(self, shard, driver_tracing):
        server, client = shard
        client.best_block()
        client.ping(b"ok")
        data = client.get_trace_spans()
        assert data["traceId"] == server.tracer.trace_id
        names = [s["name"] for s in data["spans"]]
        assert "bridge.serve.BestBlock" in names
        assert "bridge.serve.Ping" in names

    def test_plain_ping_still_echoes(self, shard):
        _, client = shard
        assert client.ping(b"khipu") == b"khipu"
        assert client.ping(b"hb") == b"hb"
        assert CLOCK_PROBE != b"khipu"


# --------------------------------------------------------- clock probe


class TestClockProbe:
    def test_injected_offset_recovered_within_rtt_bound(self, shard):
        """Satellite gate: shift the shard's wall anchor by a known
        3.5s — probe answers AND span encodings shift together (exactly
        a skewed host clock) — and the NTP-style estimate must land
        within the RTT/2 error bound. A small additive floor covers the
        sub-ms skew between the server's epoch_wall/epoch_perf sampling
        instants (a fixed anchoring cost, not an estimator error)."""
        server, client = shard
        skew = 3.5
        server.tracer.epoch_wall += skew
        offset, rtt = client.clock_probe(samples=7)
        assert rtt >= 0
        assert abs(offset - skew) <= rtt / 2 + 0.005, (offset, rtt)

    def test_zero_offset_loopback(self, shard):
        """Unskewed loopback: the estimate itself must be near zero."""
        _, client = shard
        offset, rtt = client.clock_probe(samples=7)
        assert abs(offset) <= rtt / 2 + 0.005

    def test_shard_timeline_descriptor(self, shard, driver_tracing):
        server, client = shard
        client.best_block()
        sh = export.shard_timeline(client, endpoint="ep-1")
        assert sh["endpoint"] == "ep-1"
        assert sh["traceId"] == server.tracer.trace_id
        assert any(
            s["name"] == "bridge.serve.BestBlock" for s in sh["spans"]
        )
        assert sh["rtt_s"] >= 0


# ------------------------------------------------------- merged trace


def _nesting_check(doc, driver_spans):
    """Every shard event whose remote parent resolves in the driver
    ring must render INSIDE that driver span's interval (non-negative
    nesting after offset correction — the acceptance gate)."""
    by_id = {s.sid: s for s in driver_spans}
    checked = 0
    for e in doc["traceEvents"]:
        if e.get("pid", 1) < 2 or e["ph"] not in ("X", "i"):
            continue
        args = e.get("args", {})
        rp = args.get("remote_parent")
        if rp is None or args.get("remote_trace") != tracer.trace_id:
            continue
        parent = by_id.get(rp)
        if parent is None:
            continue
        p0 = (parent.t0 - tracer.epoch_perf) * 1e6
        p1 = (parent.t1 - tracer.epoch_perf) * 1e6
        ts = e["ts"]
        dur = e.get("dur", 0.0)
        assert ts >= p0 - 1e-2, (e["name"], ts, p0)
        assert ts + dur <= p1 + 1e-2, (e["name"], ts + dur, p1)
        checked += 1
    return checked


class TestMergedTrace:
    def test_two_shard_replay_one_nested_trace(self, driver_tracing,
                                               tmp_path):
        """THE acceptance scenario: a driver executes blocks on two
        traced shards; the merged chrome trace is ONE document where
        every resolved shard server span nests inside its driver RPC
        span with offset-corrected timestamps, each shard under its own
        pid, with cross-process rpc flow arrows."""
        blocks = build_blocks(4)
        s1, c1 = _start_shard()
        s2, c2 = _start_shard()
        # distinct injected skews: the merge must correct each shard
        # with ITS OWN offset estimate
        s1.tracer.epoch_wall += 2.0
        s2.tracer.epoch_wall -= 1.5
        try:
            with tracer.span("driver.batch", blocks=len(blocks)):
                c1.execute_blocks(blocks[:2])
                c2.execute_blocks(blocks)
                c1.execute_blocks(blocks[2:])
            driver_spans = tracer.snapshot()
            shards = [
                export.shard_timeline(c1, endpoint="shard-a"),
                export.shard_timeline(c2, endpoint="shard-b"),
            ]
            path = tmp_path / "merged.json"
            export.dump_merged_chrome_trace(
                str(path), shards, driver_spans
            )
            doc = json.loads(path.read_text())  # valid JSON end to end

            meta = doc["otherData"]["shards"]
            assert [m["endpoint"] for m in meta] == ["shard-a", "shard-b"]
            assert meta[0]["pid"] == 2 and meta[1]["pid"] == 3
            assert abs(meta[0]["offsetSeconds"] - 2.0) < 0.1
            assert abs(meta[1]["offsetSeconds"] + 1.5) < 0.1
            # every ExecuteBlocks serve span resolved + nested
            assert meta[0]["nestedUnderDriver"] >= 2
            assert meta[1]["nestedUnderDriver"] >= 1
            assert _nesting_check(doc, driver_spans) >= 3

            # shard replay work (window spans) rides under the shard's
            # own pid — the bridge driver ran with the SERVER's tracer
            shard_names = {
                e["name"] for e in doc["traceEvents"]
                if e.get("pid") == 2 and e["ph"] in ("X", "i")
            }
            assert "bridge.serve.ExecuteBlocks" in shard_names
            # cross-process rpc flow arrows come in s/f pairs that
            # jump from pid 1 to the shard pid
            starts = {
                e["id"]: e for e in doc["traceEvents"]
                if e["ph"] == "s" and e.get("cat") == "rpc"
            }
            finishes = [
                e for e in doc["traceEvents"]
                if e["ph"] == "f" and e.get("cat") == "rpc"
            ]
            assert finishes and starts
            for f in finishes:
                assert starts[f["id"]]["pid"] == 1
                assert f["pid"] >= 2
        finally:
            c1.close(); c2.close()
            s1.stop(); s2.stop()

    def test_cluster_collect_traces_feeds_merge(self, driver_tracing):
        """ShardedNodeClient.collect_traces pulls every live member's
        timeline — the khipu_dump_chrome_trace cluster path."""
        from khipu_tpu.cluster import ShardedNodeClient

        s1, c1 = _start_shard()
        s2, c2 = _start_shard()
        try:
            # endpoints are only used as factory keys here
            eps = ["a", "b"]
            chans = {"a": c1, "b": c2}
            cl = ShardedNodeClient(
                eps, replication=1, max_retries=0,
                channel_factory=lambda ep: chans[ep],
                sleep=lambda s: None,
            )
            c1.best_block()
            shards = cl.collect_traces(probe_samples=2)
            assert {sh["endpoint"] for sh in shards} == {"a", "b"}
            for sh in shards:
                assert "offset_s" in sh and "spans" in sh
            doc = export.merged_chrome_trace(shards)
            json.dumps(doc)
            assert len(doc["otherData"]["shards"]) == 2
        finally:
            c1.close(); c2.close()
            s1.stop(); s2.stop()


# ------------------------------------------------- per-driver tracers


class TestPerDriverTracers:
    def test_driver_owned_ring_is_isolated(self):
        """A ReplayDriver handed its own Tracer records there — the
        module-global ring stays empty (the ROADMAP isolation note)."""
        blocks = build_blocks(4)
        cfg = dataclasses.replace(
            CFG,
            sync=SyncConfig(
                parallel_tx=False, commit_window_blocks=2,
                pipeline_depth=2,
            ),
        )
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec(alloc=ALLOC))
        mine = Tracer(capacity=4096)
        mine.enable()
        assert not tracer.enabled
        before = tracer.recorded
        ReplayDriver(bc, cfg, tracer=mine).replay(blocks)
        spans = mine.snapshot()
        names = {s.name for s in spans}
        # driver AND collector-thread spans landed in the private ring
        assert {"window.build", "window.seal", "window.collect",
                "window.persist"} <= names
        assert tracer.recorded == before  # module ring untouched

    def test_two_bridge_servers_disjoint_rings(self, driver_tracing):
        """Two in-process shards never interleave span rings, and their
        trace ids differ — GetTraceSpans pulls stay attributable."""
        s1, c1 = _start_shard()
        s2, c2 = _start_shard()
        try:
            assert s1.tracer is not s2.tracer
            assert s1.tracer.trace_id != s2.tracer.trace_id
            c1.best_block()
            assert any(
                s.name == "bridge.serve.BestBlock"
                for s in s1.tracer.snapshot()
            )
            assert not any(
                s.name == "bridge.serve.BestBlock"
                for s in s2.tracer.snapshot()
            )
        finally:
            c1.close(); c2.close()
            s1.stop(); s2.stop()

    def test_use_tracer_is_thread_scoped_and_nested(self):
        a, b = Tracer(), Tracer()
        a.enable(); b.enable()
        assert current_tracer() is tracer
        with use_tracer(a):
            assert current_tracer() is a
            with use_tracer(b):
                assert current_tracer() is b
            assert current_tracer() is a
        assert current_tracer() is tracer

    def test_service_board_owns_one_tracer(self, tmp_path):
        """The board's tracer is THE ring its bridge serves from."""
        from khipu_tpu.service_board import ServiceBoard

        board = ServiceBoard(CFG)
        try:
            assert isinstance(board.tracer, Tracer)
            assert board.tracer is not tracer
            port = board.start_bridge(port=0)
            assert port > 0
            assert board._bridge_server.tracer is board.tracer
        finally:
            board.shutdown()
