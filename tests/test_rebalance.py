"""Elastic membership (khipu_tpu/cluster/rebalance.py): epoch-fenced
ring transitions, exact movement planning, crash-safe live join/retire
over fake transports, the 120-seed InjectedDeath sweep across every
``rebalance.*`` chaos seam, and the ISSUE-11 acceptance scenario —
join-4th-mid-sync, kill-mid-stream, rejoin, cutover, retire-an-original
under live load with zero wrong reads."""

import threading

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.chaos import (
    FaultPlan,
    FaultRule,
    InjectedDeath,
    active,
)
from khipu_tpu.cluster import (
    HashRing,
    Rebalancer,
    RebalanceError,
    RebalanceAborted,
    ShardedNodeClient,
    movement_plan,
)
from khipu_tpu.cluster.rebalance import moved_fraction
from khipu_tpu.cluster.ring import RING_SIZE, _point


def _val(i: int) -> bytes:
    return b"mpt node rlp bytes #%d" % i


def _key(v: bytes) -> bytes:
    return keccak256(v)


def _dataset(n: int):
    return {_key(_val(i)): _val(i) for i in range(n)}


# ------------------------------------------------- fake transport


class FakeShard:
    """In-memory BridgeClient stand-in with the rebalance surface:
    cursor-paged ``stream_node_data`` over the store, content-addressed
    ``put_node_data``."""

    def __init__(self, store=None, fail=False):
        self.store = dict(store or {})
        self.fail = fail
        self.stream_calls = 0
        self.on_stream = None  # test hook, runs before each page
        self.corrupt_stream = False  # flip bytes in streamed pages

    def get_node_data(self, hashes):
        if self.fail:
            raise ConnectionError("shard down")
        return {h: self.store[h] for h in hashes if h in self.store}

    def put_node_data(self, nodes):
        if self.fail:
            raise ConnectionError("shard down")
        self.store.update(nodes)
        return len(nodes)

    def stream_node_data(self, ranges, cursor, count):
        self.stream_calls += 1
        if self.on_stream is not None:
            self.on_stream(self)
        if self.fail:
            raise ConnectionError("shard down")
        snap = dict(self.store)  # live writers mutate concurrently
        keys = sorted(
            k for k in snap
            if cursor < k
            and any(lo <= _point(k) < hi for lo, hi in ranges)
        )
        page = keys[:count]
        done = len(keys) <= count
        nxt = page[-1] if page else bytes(cursor)
        pairs = [(k, snap[k]) for k in page]
        if self.corrupt_stream and pairs:
            k, v = pairs[0]
            pairs[0] = (k, b"evil " + v)  # wire corruption
        return done, nxt, pairs

    def ping(self, payload=b""):
        if self.fail:
            raise ConnectionError("shard down")
        return payload

    def close(self):
        pass


def make_cluster(members, data=None, extra=(), **kwargs):
    """Client over ``members`` + a Rebalancer; ``extra`` endpoints get
    FakeShards in the transport map but stay outside the ring (join
    candidates)."""
    shards = {ep: FakeShard() for ep in (*members, *extra)}
    kwargs.setdefault("replication", 2)
    kwargs.setdefault("vnodes", 8)  # keeps snapshot rebuilds cheap
    kwargs.setdefault("max_retries", 1)
    kwargs.setdefault("sleep", lambda s: None)
    cl = ShardedNodeClient(
        list(members),
        channel_factory=lambda ep: shards[ep],
        **kwargs,
    )
    rb = Rebalancer(cl, batch=64)
    if data:
        cl.replicate(data)
    return cl, rb, shards


# ---------------------------------------------------- transitions


class TestRingTransition:
    def test_begin_stages_next_epoch_without_commit(self):
        ring = HashRing(["a", "b"], replication=2, vnodes=8)
        e0 = ring.epoch
        old, new = ring.begin_transition(["a", "b", "c"])
        assert (old.epoch, new.epoch) == (e0, e0 + 1)
        assert ring.epoch == e0  # committed epoch unchanged
        assert ring.in_transition
        assert ring.members == ("a", "b")  # placement unchanged

    def test_only_one_transition_open(self):
        ring = HashRing(["a", "b"], replication=2, vnodes=8)
        ring.begin_transition(["a", "b", "c"])
        with pytest.raises(RuntimeError):
            ring.begin_transition(["a", "b", "d"])

    def test_no_op_transition_rejected(self):
        ring = HashRing(["a", "b"], replication=2, vnodes=8)
        with pytest.raises(ValueError):
            ring.begin_transition(["b", "a", "a"])

    def test_read_chain_new_then_old_write_chains_union(self):
        ring = HashRing(["a", "b", "c"], replication=2, vnodes=8)
        old, new = ring.begin_transition(["a", "b", "c", "d"])
        for i in range(200):
            k = _key(_val(i))
            pt = _point(k)
            rc = ring.read_chain(k)
            wc = ring.write_chains(k)
            # new-epoch owners first, then any old owner not already in
            expect = list(new.chain_at(pt))
            for ep in old.chain_at(pt):
                if ep not in expect:
                    expect.append(ep)
            assert rc == expect
            # writes land in the union of both worlds
            assert set(wc) == set(old.chain_at(pt)) | set(
                new.chain_at(pt)
            )
            assert len(wc) == len(set(wc))

    def test_commit_is_atomic_cutover(self):
        ring = HashRing(["a", "b"], replication=2, vnodes=8)
        _, new = ring.begin_transition(["a", "b", "c"])
        committed = ring.commit_transition()
        assert committed is new
        assert ring.epoch == new.epoch
        assert not ring.in_transition
        assert set(ring.members) == {"a", "b", "c"}
        with pytest.raises(RuntimeError):
            ring.commit_transition()

    def test_abort_leaves_committed_ring_untouched(self):
        ring = HashRing(["a", "b"], replication=2, vnodes=8)
        before = {
            _key(_val(i)): ring.replicas_for(_key(_val(i)))
            for i in range(100)
        }
        ring.begin_transition(["a", "b", "c"])
        assert ring.abort_transition() is True
        assert ring.abort_transition() is False  # nothing open now
        assert ring.epoch == 1 and not ring.in_transition
        for k, chain in before.items():
            assert ring.replicas_for(k) == chain

    def test_direct_membership_change_auto_aborts(self):
        ring = HashRing(["a", "b"], replication=2, vnodes=8)
        ring.begin_transition(["a", "b", "c"])
        assert ring.add("x") is True
        assert not ring.in_transition
        assert ring.transition_aborts == 1
        ring.begin_transition(["a", "b", "x", "c"])
        assert ring.remove("x") is True
        assert not ring.in_transition
        assert ring.transition_aborts == 2

    def test_epoch_monotone_across_membership_changes(self):
        ring = HashRing(["a"], replication=1, vnodes=8)
        seen = [ring.epoch]
        ring.add("b")
        seen.append(ring.epoch)
        ring.begin_transition(["a", "b", "c"])
        ring.commit_transition()
        seen.append(ring.epoch)
        ring.remove("c")
        seen.append(ring.epoch)
        assert seen == sorted(seen) and len(set(seen)) == len(seen)


class TestChainShortCircuit:
    def test_single_member_walks_one_point(self):
        """Regression (ISSUE 11 satellite): ``chain_at`` short-circuits
        at ``len(members)`` distinct endpoints — a 1-member ring with
        replication=2 must not walk all vnode points hunting for a
        second endpoint that cannot exist."""
        ring = HashRing(["only"], replication=2, vnodes=64)

        class CountingOwners(list):
            reads = 0

            def __getitem__(self, i):
                CountingOwners.reads += 1
                return list.__getitem__(self, i)

        ring._snap.owners = CountingOwners(ring._snap.owners)
        assert ring.replicas_for(_key(_val(1))) == ["only"]
        assert CountingOwners.reads == 1

    def test_chain_capped_by_membership_mid_transition(self):
        ring = HashRing(["a"], replication=2, vnodes=8)
        old, new = ring.begin_transition(["a", "b"])
        assert old.chain_at(123) == ["a"]
        assert len(new.chain_at(123)) == 2


# -------------------------------------------------- movement plan


class TestMovementPlan:
    def test_join_moves_bounded_fraction_of_keys(self):
        """Property (ISSUE 11 satellite): adding 1 endpoint to an
        N-member ring remaps at most ``1.5/(N+1)`` of 10k keys."""
        n = 4
        ring = HashRing(
            [f"s{i}" for i in range(n)], replication=1, vnodes=64
        )
        keys = [_key(_val(i)) for i in range(10_000)]
        before = {k: ring.primary_for(k) for k in keys}
        old, new = ring.begin_transition(
            [f"s{i}" for i in range(n)] + ["joiner"]
        )
        moved = sum(
            1 for k in keys if new.replicas_for(k) != [before[k]]
        )
        assert moved / len(keys) <= 1.5 / (n + 1)
        # the plan's analytic fraction agrees with the sampled one
        frac = moved_fraction(movement_plan(old, new))
        assert abs(frac - moved / len(keys)) < 0.05

    def test_remove_restores_exact_prior_ownership(self):
        ring = HashRing(["a", "b", "c"], replication=2, vnodes=64)
        keys = [_key(_val(i)) for i in range(2_000)]
        before = {k: ring.replicas_for(k) for k in keys}
        ring.add("d")
        ring.remove("d")
        for k in keys:
            assert ring.replicas_for(k) == before[k]

    def test_plan_ranges_exactly_cover_gaining_keys(self):
        """movement_plan is exact, not sampled: a key falls inside some
        MovedRange iff its new chain gained an endpoint."""
        ring = HashRing(["a", "b", "c"], replication=2, vnodes=8)
        old, new = ring.begin_transition(["a", "b", "c", "d"])
        plan = movement_plan(old, new)
        for i in range(3_000):
            k = _key(_val(i))
            pt = _point(k)
            oc = old.chain_at(pt)
            gainers = [
                ep for ep in new.chain_at(pt) if ep not in oc
            ]
            hit = [
                r for r in plan if r.lo <= pt < r.hi
            ]
            if gainers:
                assert len(hit) == 1
                assert list(hit[0].gainers) == gainers
                assert list(hit[0].sources) == oc
            else:
                assert hit == []

    def test_plan_ranges_disjoint_and_in_ring(self):
        ring = HashRing(["a", "b"], replication=1, vnodes=16)
        old, new = ring.begin_transition(["a", "b", "c"])
        plan = sorted(movement_plan(old, new), key=lambda r: r.lo)
        for r in plan:
            assert 0 <= r.lo < r.hi <= RING_SIZE
        for r1, r2 in zip(plan, plan[1:]):
            assert r1.hi <= r2.lo


# ----------------------------------------------- join and retire


class TestJoinRetire:
    def test_join_streams_then_cuts_over(self):
        data = _dataset(300)
        cl, rb, shards = make_cluster(["a", "b", "c"], data,
                                      extra=("d",))
        e0 = cl.ring.epoch
        streamed = rb.join("d")
        assert streamed > 0
        assert set(cl.ring.members) == {"a", "b", "c", "d"}
        assert cl.ring.epoch == e0 + 1
        assert not cl.ring.in_transition
        assert rb.completed == 1 and rb.state == "idle"
        # every key the new epoch assigns to d actually landed on d
        for k, v in data.items():
            if "d" in cl.ring.replicas_for(k):
                assert shards["d"].store[k] == v
        # full readback, bit-exact
        assert cl.fetch(list(data)) == data
        assert cl.metrics["d"].rebalanced == streamed
        assert cl._full_ring.members == cl.ring.members

    def test_retire_drains_then_drops(self):
        data = _dataset(300)
        cl, rb, shards = make_cluster(["a", "b", "c"], data)
        rb.retire("a")
        assert set(cl.ring.members) == {"b", "c"}
        assert not cl.ring.in_transition
        # the retired shard is gone from the configured ring too
        assert set(cl._full_ring.members) == {"b", "c"}
        # all keys still fully replicated across the survivors
        for k, v in data.items():
            for ep in cl.ring.replicas_for(k):
                assert shards[ep].store[k] == v
        assert cl.fetch(list(data)) == data

    def test_join_then_retire_roundtrip_ownership(self):
        data = _dataset(200)
        cl, rb, _ = make_cluster(["a", "b", "c"], data, extra=("d",))
        before = {k: cl.ring.replicas_for(k) for k in data}
        rb.join("d")
        rb.retire("d")
        for k in data:
            assert cl.ring.replicas_for(k) == before[k]
        assert cl.fetch(list(data)) == data

    def test_join_validates_membership(self):
        cl, rb, _ = make_cluster(["a", "b"], _dataset(10))
        with pytest.raises(ValueError):
            rb.join("a")

    def test_retire_validates_membership_and_last_member(self):
        cl, rb, _ = make_cluster(["a", "b"], _dataset(10))
        with pytest.raises(ValueError):
            rb.retire("zz")
        cl2, rb2, _ = make_cluster(["solo"], replication=1)
        with pytest.raises(ValueError):
            rb2.retire("solo")

    def test_corrupt_stream_aborts_to_committed_epoch(self):
        data = _dataset(100)
        cl, rb, shards = make_cluster(["a", "b", "c"], data,
                                      extra=("d",))

        for ep in ("a", "b", "c"):
            shards[ep].corrupt_stream = True
        e0 = cl.ring.epoch
        with pytest.raises(RebalanceError):
            rb.join("d")
        assert cl.ring.epoch == e0
        assert not cl.ring.in_transition
        assert set(cl.ring.members) == {"a", "b", "c"}
        assert rb.aborts == 1 and rb.state == "idle"

    def test_member_death_mid_stream_aborts(self):
        data = _dataset(200)
        cl, rb, shards = make_cluster(["a", "b", "c"], data,
                                      extra=("d",))
        fired = []

        def kill_b(shard):
            if not fired:
                fired.append(1)
                cl.mark_dead("b")

        for ep in ("a", "b", "c"):
            shards[ep].on_stream = kill_b
        e_members = set(cl.ring.members)
        with pytest.raises(RebalanceAborted):
            rb.join("d")
        assert rb.aborts == 1
        assert not cl.ring.in_transition
        assert set(cl.ring.members) == e_members - {"b"}
        # the committed (post-death) ring still serves every key
        assert cl.fetch(list(data)) == data

    def test_second_rebalance_while_pending_rejected(self):
        cl, rb, _ = make_cluster(["a", "b"], _dataset(10),
                                 extra=("c",))
        rb._begin("join", "c", ("a", "b", "c"))
        with pytest.raises(RuntimeError):
            rb.join("c")


# ------------------------------------------------- crash recovery


def _die(site, seed=0, after=0):
    return FaultPlan(seed=seed, rules=[
        FaultRule(site=site, kind="die", after=after, times=1)
    ])


class TestCrashRecovery:
    def test_death_mid_stream_then_resume(self):
        data = _dataset(300)
        cl, rb, _ = make_cluster(["a", "b", "c"], data, extra=("d",))
        e0 = cl.ring.epoch
        with active(_die("rebalance.stream", after=1)):
            with pytest.raises(InjectedDeath):
                rb.join("d")
        # crash left the committed epoch serving and a transition open
        assert cl.ring.epoch == e0
        assert cl.fetch(list(data)) == data
        assert rb.recover() == "resumed"
        assert set(cl.ring.members) == {"a", "b", "c", "d"}
        assert cl.ring.epoch == e0 + 1
        assert cl.fetch(list(data)) == data

    def test_death_before_plan_then_rollback_is_bookkeeping(self):
        data = _dataset(50)
        cl, rb, _ = make_cluster(["a", "b"], data, extra=("c",))
        with active(_die("rebalance.plan")):
            with pytest.raises(InjectedDeath):
                rb.join("c")
        assert not cl.ring.in_transition  # died before staging
        assert rb.recover() == "rolled_back"
        assert set(cl.ring.members) == {"a", "b"}
        assert rb.recover() == "idle"

    def test_dead_target_rolls_back_and_records_debt(self):
        data = _dataset(300)
        cl, rb, shards = make_cluster(["a", "b", "c"], data,
                                      extra=("d",))
        e0 = cl.ring.epoch
        with active(_die("rebalance.stream", after=2)):
            with pytest.raises(InjectedDeath):
                rb.join("d")
        assert rb.keys_streamed > 0  # at least one page landed on d
        shards["d"].fail = True  # the joiner died with us
        assert rb.recover() == "rolled_back"
        assert cl.ring.epoch == e0
        assert set(cl.ring.members) == {"a", "b", "c"}
        assert rb.aborts == 1
        # the half-streamed keys became anti-entropy debt for d
        assert cl._missed.get("d")
        assert cl.fetch(list(data)) == data

    def test_death_at_cutover_then_resume_completes(self):
        data = _dataset(200)
        cl, rb, _ = make_cluster(["a", "b", "c"], data, extra=("d",))
        e0 = cl.ring.epoch
        with active(_die("rebalance.cutover")):
            with pytest.raises(InjectedDeath):
                rb.join("d")
        # the cutover seam fires BEFORE commit: old epoch authoritative
        assert cl.ring.epoch == e0
        assert cl.fetch(list(data)) == data
        assert rb.recover() == "resumed"
        assert cl.ring.epoch == e0 + 1
        assert cl.fetch(list(data)) == data

    def test_die_sweep_never_serves_wrong_bytes(self):
        """ISSUE 11 acceptance: 120 seeded InjectedDeath runs across
        every ``rebalance.*`` seam; after recover() the cluster is at
        exactly the old or the new epoch (never between) and every key
        reads back bit-exact."""
        sites = (
            "rebalance.plan", "rebalance.stream",
            "rebalance.cutover", "rebalance.retire",
        )
        data = _dataset(120)
        runs = 0
        for site in sites:
            for seed in range(30):
                runs += 1
                cl, rb, shards = make_cluster(
                    ["a", "b", "c"], data, extra=("d",)
                )
                kind = "retire" if site == "rebalance.retire" else "join"
                target = "a" if kind == "retire" else "d"
                old_members = set(cl.ring.members)
                new_members = (
                    old_members - {target} if kind == "retire"
                    else old_members | {target}
                )
                e0 = cl.ring.epoch
                plan = _die(site, seed=seed, after=seed % 4)
                died = False
                with active(plan):
                    try:
                        getattr(rb, kind)(target)
                    except InjectedDeath:
                        died = True
                    except RebalanceError:
                        pass
                # no injected plan any more: settle the wreckage
                outcome = rb.recover()
                assert not cl.ring.in_transition, (site, seed)
                members = set(cl.ring.members)
                if members == old_members:
                    assert cl.ring.epoch == e0, (site, seed)
                else:
                    assert members == new_members, (site, seed)
                    assert cl.ring.epoch == e0 + 1, (site, seed)
                # bit-exact reads from whichever epoch won
                assert cl.fetch(list(data)) == data, (site, seed)
                if died:
                    # "idle" only when death hit BEFORE any state was
                    # created (the rebalance.retire entry seam)
                    assert outcome in (
                        "resumed", "rolled_back", "idle"
                    ), (site, seed)
        assert runs == 120


# --------------------------------------------------- acceptance


class TestAcceptanceLiveLoad:
    def test_join_kill_rejoin_cutover_retire_under_load(self):
        """3-shard cluster under live read/write load: join a 4th
        mid-sync, kill it mid-stream (InjectedDeath), rejoin via
        recover(), cut over, then retire an original — zero wrong
        reads, read-your-writes holds throughout, final ownership
        equals a fresh ring of the survivors."""
        data = _dataset(250)
        cl, rb, shards = make_cluster(["a", "b", "c"], data,
                                      extra=("d",))
        errors = []
        stop = threading.Event()
        written = dict(data)
        wlock = threading.Lock()

        def writer():
            i = 100_000
            while not stop.is_set():
                v = _val(i)
                k = _key(v)
                try:
                    cl.replicate({k: v})
                    got = cl.fetch([k])
                    if got != {k: v}:  # read-your-writes
                        errors.append(("ryw", k.hex()[:12], got))
                except Exception as e:
                    errors.append(("write", type(e).__name__, str(e)))
                with wlock:
                    written[k] = v
                i += 1

        def reader():
            n = 0
            while not stop.is_set():
                with wlock:
                    items = list(written.items())
                k, v = items[n % len(items)]
                try:
                    got = cl.fetch([k])
                    if got != {k: v}:
                        errors.append(("read", k.hex()[:12], got))
                except Exception as e:
                    errors.append(("read", type(e).__name__, str(e)))
                n += 1

        threads = [
            threading.Thread(target=writer, daemon=True),
            threading.Thread(target=reader, daemon=True),
        ]
        for t in threads:
            t.start()
        try:
            # join the 4th shard and kill the rebalance mid-stream
            with active(_die("rebalance.stream", after=1)):
                with pytest.raises(InjectedDeath):
                    rb.join("d")
            # rejoin: the staged epoch is still open, targets answer
            assert rb.recover() == "resumed"
            assert set(cl.ring.members) == {"a", "b", "c", "d"}
            # retire an ORIGINAL member under the same load
            rb.retire("a")
            assert set(cl.ring.members) == {"b", "c", "d"}
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert errors == []
        assert not cl.ring.in_transition
        # final ownership == a fresh ring of exactly the survivors
        fresh = HashRing(["b", "c", "d"], replication=2, vnodes=8)
        with wlock:
            snapshot = dict(written)
        for k in list(snapshot)[:500]:
            assert cl.ring.replicas_for(k) == fresh.replicas_for(k)
        # every key ever written reads back bit-exact
        assert cl.fetch(list(snapshot)) == snapshot
        assert rb.completed == 2  # the resumed join + the retire


# ----------------------------------------------- observability


class TestObservability:
    def test_cluster_registry_families_pinned(self):
        """Regression (ISSUE 11 satellite): the anti-entropy debt
        gauges are exported as first-class registry families."""
        from khipu_tpu.observability.registry import REGISTRY

        cl, rb, _ = make_cluster(["a", "b"], _dataset(5))
        cl._record_missed("a", [b"\x01" * 32])
        text = REGISTRY.prometheus_text()
        assert "khipu_cluster_missed_keys" in text
        assert "khipu_cluster_missed_dropped_total" in text
        assert "khipu_cluster_epoch" in text
        for fam in (
            "khipu_rebalance_epoch",
            "khipu_rebalance_in_transition",
            "khipu_rebalance_keys_streamed_total",
            "khipu_rebalance_keys_placed_total",
            "khipu_rebalance_completed_total",
            "khipu_rebalance_aborts_total",
            "khipu_rebalance_moved_fraction",
        ):
            assert fam in text, fam

    def test_metrics_snapshot_carries_rebalance_block(self):
        cl, rb, _ = make_cluster(["a", "b"], _dataset(20),
                                 extra=("c",))
        rb.join("c")
        snap = cl.metrics_snapshot()
        assert snap["epoch"] == cl.ring.epoch
        assert snap["inTransition"] is False
        assert snap["rebalance"]["completed"] == 1
        assert snap["rebalance"]["state"] == "idle"
        assert snap["rebalance"]["keysStreamed"] == rb.keys_streamed

    def test_rebalance_pressure_signal(self):
        from khipu_tpu.serving import rebalance_pressure

        cl, rb, _ = make_cluster(["a", "b"], _dataset(10))
        sig = rebalance_pressure(rb)
        assert sig.signal_name == "rebalance"
        assert sig() == 0.0  # idle: the signal costs nothing
        cl.ring.begin_transition(["a", "b", "c"])
        assert sig() == pytest.approx(0.88)
        cl.ring.abort_transition()
        assert sig() == 0.0

    def test_watchdog_rebalance_stuck_edge_triggered(self):
        from khipu_tpu.config import TelemetryConfig
        from khipu_tpu.observability.telemetry import (
            WATCHDOG_KINDS,
            Watchdog,
        )

        assert "rebalance_stuck" in WATCHDOG_KINDS
        state = {"open": False, "prog": 0}
        dog = Watchdog(
            TelemetryConfig(enabled=True, stall_after_s=5.0),
            pipeline={},
            rebalance=lambda: (state["open"], state["prog"]),
        )
        # clean run: nothing trips, the kind exports as zero
        assert dog.check_once(now=0.0) == []
        assert dog.trips["rebalance_stuck"] == 0
        assert (
            "khipu_watchdog_trips_total", "counter",
            {"kind": "rebalance_stuck"}, 0,
        ) in dog._registry_samples()
        # transition opens and progress goes flat: one trip per episode
        state["open"] = True
        assert dog.check_once(now=10.0) == []  # arms
        assert dog.check_once(now=16.0) == ["rebalance_stuck"]
        assert dog.check_once(now=30.0) == []  # edge, not level
        # progress re-arms the detector
        state["prog"] = 42
        assert dog.check_once(now=31.0) == []
        assert dog.check_once(now=37.0) == ["rebalance_stuck"]
        # closing the transition re-arms too
        state["open"] = False
        assert dog.check_once(now=50.0) == []
        assert dog.trips["rebalance_stuck"] == 2
