"""Cost-model-adaptive commit tests (sync/adaptive.py): the backend
probe gate, the EWMA Schmitt trigger (flip without flapping), the
upload-verdict depth hint, and the end-to-end CPU fallback — a replay
with the adaptive controller on a slow-d2d backend commits on the host
path and still lands on the bit-exact chain."""

import dataclasses

import pytest

import khipu_tpu.sync.adaptive as adaptive_mod
from khipu_tpu.config import SyncConfig
from khipu_tpu.sync.adaptive import (
    ADAPTIVE_GAUGES,
    AdaptiveCommitController,
    ProbeResult,
    probe_backend,
)


def _sync_cfg(**overrides):
    overrides.setdefault("adaptive_probe", False)  # unit tests doctor it
    return SyncConfig(**overrides)


def _slow_probe(platform="doctored"):
    """A backend where the d2d gather LOSES to the host memcpy 100x —
    the BENCH_r07 1-core-CPU shape."""
    return ProbeResult(platform, 1e6, 1e8, False)


def _fast_probe(platform="doctored-hbm"):
    return ProbeResult(platform, 1e11, 1e9, True)


class TestProbeGate:
    def test_doctored_slow_d2d_backend_starts_in_host_mode(
            self, monkeypatch):
        """THE acceptance flip: on a backend whose 'device' memory is
        host RAM the probe cannot clear the margin, so the controller
        downgrades to host commit BEFORE the first window — no 34 s
        device fixpoint is ever paid."""
        monkeypatch.setattr(
            adaptive_mod, "probe_backend", lambda margin: _slow_probe()
        )
        ctrl = AdaptiveCommitController(
            _sync_cfg(adaptive_probe=True), device_cap=True
        )
        assert ctrl.mode() == "host"
        assert not ctrl.device_mode
        assert ctrl.flips == 1  # the probe downgrade is a counted flip
        assert ADAPTIVE_GAUGES["device_mode"] == 0

    def test_fast_d2d_backend_keeps_device_mode(self, monkeypatch):
        monkeypatch.setattr(
            adaptive_mod, "probe_backend", lambda margin: _fast_probe()
        )
        ctrl = AdaptiveCommitController(
            _sync_cfg(adaptive_probe=True), device_cap=True
        )
        assert ctrl.mode() == "device"
        assert ctrl.flips == 0

    def test_no_device_cap_never_probes_never_flips(self):
        ctrl = AdaptiveCommitController(
            _sync_cfg(adaptive_probe=True), device_cap=False
        )
        assert ctrl.mode() == "host"
        assert ctrl.probe is None
        # a miraculous device EWMA cannot upgrade past the config cap
        ctrl._ewma["device"] = 1e-12
        ctrl._dwell = 10**6
        ctrl.observe_window("host", 100, 1.0)
        assert ctrl.mode() == "host" and ctrl.flips == 0

    def test_real_cpu_probe_is_cached_and_consistent(self):
        """Smoke the real measurement on whatever backend the test
        host has: sane rates, process-cache hit on the second call."""
        p1 = probe_backend(margin=1.5)
        p2 = probe_backend(margin=1.5)
        assert p1 is p2  # cached per platform
        assert p1.d2d_bytes_per_s >= 0 and p1.memcpy_bytes_per_s >= 0


class TestSchmittTrigger:
    def _device_ctrl(self, **overrides):
        ctrl = AdaptiveCommitController(_sync_cfg(**overrides),
                                        device_cap=True)
        ctrl.probe = _fast_probe()  # probe said ok; EWMAs now decide
        return ctrl

    def test_slow_device_windows_flip_to_host_after_dwell(self):
        """Device windows costing 100x the host floor per hash must
        flip the mode — but only once ``adaptive_dwell_windows`` have
        been spent in device mode (no knee-jerk on the first bad
        window), and the flip must not oscillate afterwards."""
        ctrl = self._device_ctrl()
        dwell = ctrl.cfg.adaptive_dwell_windows
        slow = 100.0 * ctrl.host_floor_s  # per-hash, ratio 100 >> 2.0
        for i in range(dwell - 1):
            ctrl.observe_window("device", 1000, 1000 * slow)
            assert ctrl.mode() == "device", f"flipped early at {i}"
        assert ctrl.flaps_suppressed == dwell - 1  # wanted, held back
        ctrl.observe_window("device", 1000, 1000 * slow)
        assert ctrl.mode() == "host"
        assert ctrl.flips == 1
        # more slow-device evidence must NOT flip again (already host)
        for _ in range(3 * dwell):
            ctrl.observe_window("host", 1000, 1000 * ctrl.host_floor_s)
        assert ctrl.mode() == "host" and ctrl.flips == 1

    def test_hysteresis_band_blocks_flap(self):
        """A ratio inside the band (flip_back_ratio < r < flip_ratio)
        moves NOTHING in either mode — the band is the no-trade zone
        that kills oscillation on noisy backends."""
        ctrl = self._device_ctrl()
        ctrl._dwell = 10**6  # dwell satisfied; only the band holds
        mid = 1.0  # host == device per-hash: inside (0.5, 2.0)
        for _ in range(20):
            ctrl.observe_window("device", 1000,
                                1000 * mid * ctrl.host_floor_s)
        assert ctrl.mode() == "device" and ctrl.flips == 0

    def test_flip_back_needs_probe_ok_and_low_ratio(self):
        """Host mode flips back to device only when the device EWMA
        drops below ``flip_back_ratio`` x host AND the probe cleared
        the backend — a slow-d2d backend stays host forever."""
        ctrl = self._device_ctrl()
        ctrl.device_mode = False  # already downgraded
        ctrl._ewma["device"] = 0.1 * ctrl.host_floor_s  # 10x cheaper
        ctrl._dwell = 10**6
        ctrl.probe = _slow_probe()
        ctrl.observe_window("host", 1000, 1000 * ctrl.host_floor_s)
        assert ctrl.mode() == "host"  # probe veto holds
        ctrl.probe = _fast_probe()
        ctrl.observe_window("host", 1000, 1000 * ctrl.host_floor_s)
        assert ctrl.mode() == "device"
        assert ctrl.flips == 1

    def test_gauges_track_the_controller(self):
        ctrl = self._device_ctrl()
        ctrl.observe_window("device", 10, 10 * ctrl.host_floor_s)
        assert ADAPTIVE_GAUGES["windows_observed"] == ctrl.windows
        assert ADAPTIVE_GAUGES["device_mode"] == int(ctrl.device_mode)
        assert ADAPTIVE_GAUGES["ewma_device_hash_s"] > 0


class TestDepthHint:
    def _ctrl(self):
        return AdaptiveCommitController(_sync_cfg(), device_cap=False)

    def test_bytes_bound_upload_deepens_pipeline(self, monkeypatch):
        ctrl = self._ctrl()
        monkeypatch.setattr(
            adaptive_mod, "classify",
            lambda achieved, floors: {"bound": "bytes-bound"},
        )
        base = ctrl.cfg.pipeline_depth
        ctrl.note_upload(1 << 20, 0.5)
        assert ctrl.depth_hint == min(ctrl.cfg.adaptive_depth_max,
                                      base + 1)
        for _ in range(10):  # saturates at the cap, never beyond
            ctrl.note_upload(1 << 20, 0.5)
        assert ctrl.depth_hint == ctrl.cfg.adaptive_depth_max
        assert ADAPTIVE_GAUGES["depth_hint"] == ctrl.depth_hint

    def test_fixed_overhead_upload_shallows_pipeline(self, monkeypatch):
        ctrl = self._ctrl()
        monkeypatch.setattr(
            adaptive_mod, "classify",
            lambda achieved, floors: {"bound": "fixed-overhead"},
        )
        for _ in range(10):
            ctrl.note_upload(64, 0.5)
        assert ctrl.depth_hint == 1  # floors at 1, never 0

    def test_zero_duration_upload_is_ignored(self):
        ctrl = self._ctrl()
        ctrl.note_upload(1 << 20, 0.0)
        assert ctrl.depth_hint is None


class TestAdaptiveReplay:
    def test_cpu_replay_flips_to_host_and_lands_bit_exact(
            self, monkeypatch):
        """End to end: a device-commit replay whose probe reports a
        slow-d2d backend must run its windows on the host path (no
        device fixpoint) and produce the identical chain — adaptive
        routing never touches state roots."""
        from tests.test_window import (
            ADDRS, CFG, ETH, MINER, chain as _chain_fixture,  # noqa: F401
        )
        from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
        from khipu_tpu.storage.storages import Storages
        from khipu_tpu.sync.replay import ReplayDriver
        from khipu_tpu.trie.bulk import host_hasher

        # build the 5-block fixture chain directly (module fixture is
        # in another file; importing the function, not the fixture)
        from khipu_tpu.sync.chain_builder import ChainBuilder
        from tests.test_window import INIT, tx

        builder = ChainBuilder(
            Blockchain(Storages(), CFG), CFG,
            GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}),
        )
        blocks = [builder.add_block(
            [tx(0, 0, None, 0, gas=300_000, payload=INIT)],
            coinbase=MINER)]
        blocks.append(builder.add_block([tx(1, 0, ADDRS[2], 123)],
                                        coinbase=MINER))
        blocks.append(builder.add_block([tx(2, 0, ADDRS[0], 1)],
                                        coinbase=MINER))

        monkeypatch.setattr(
            adaptive_mod, "probe_backend", lambda margin: _slow_probe()
        )
        cfg = dataclasses.replace(
            CFG, sync=SyncConfig(parallel_tx=False,
                                 commit_window_blocks=2,
                                 pipeline_depth=2),
        )
        assert cfg.sync.adaptive_commit  # on by default

        def _fresh():
            bc = Blockchain(Storages(), cfg)
            bc.load_genesis(
                GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS})
            )
            return bc

        bc = _fresh()
        driver = ReplayDriver(bc, cfg, device_commit=True)
        driver.hasher = host_hasher
        stats = driver.replay(blocks)
        assert stats.blocks == 3
        assert bc.get_header_by_number(3).hash == blocks[-1].hash
        assert ADAPTIVE_GAUGES["device_mode"] == 0
        assert ADAPTIVE_GAUGES["windows_observed"] >= 1

        # oracle: plain host replay, no device commit, no adaptive
        ref_cfg = dataclasses.replace(
            cfg, sync=dataclasses.replace(cfg.sync,
                                          adaptive_commit=False),
        )
        ref = _fresh()
        ReplayDriver(ref, ref_cfg).replay(blocks)
        for n in range(1, 4):
            assert (bc.get_header_by_number(n).hash
                    == ref.get_header_by_number(n).hash)
        assert (bc.get_header_by_number(3).state_root
                == ref.get_header_by_number(3).state_root)
