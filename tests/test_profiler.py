"""Data-movement ledger tests (khipu_tpu/observability/profiler.py):
exact byte accounting against a known-size node fixture, zero-cost
disabled mode (bit-exact replay, no extra device syncs), chrome counter
tracks, the bench --compare regression gate, and the registry /
sampling satellites that rode along (scrape-pass collector caching,
histogram bucket overrides, deterministic per-trace-id sampling)."""

import dataclasses
import json
import random

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import (
    ObservabilityConfig,
    SyncConfig,
    fixture_config,
)
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.observability import export, recorder
from khipu_tpu.observability.profiler import (
    COLLECT_CLASSES,
    D2H,
    H2D,
    HOST,
    LEDGER,
    TransferLedger,
    _NULL_TRANSFER,
)
from khipu_tpu.observability.registry import MetricsRegistry
from khipu_tpu.observability.trace import trace_sampled, tracer
from khipu_tpu.storage.device_mirror import TILE, DeviceNodeMirror
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.replay import ReplayDriver

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(4)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18


@pytest.fixture(autouse=True)
def _clean_ledger():
    """Every test starts and ends with a disabled, empty ledger (the
    registry counters persist by design — they are monotonic)."""
    LEDGER.disable()
    LEDGER.reset()
    yield
    LEDGER.disable()
    LEDGER.reset()


def _chain(n_blocks=8, txs_per_block=8):
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG,
        GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}),
    )
    blocks = []
    nonces = [0] * 4
    for n in range(n_blocks):
        txs = []
        for j in range(txs_per_block):
            i = j % 4
            txs.append(
                sign_transaction(
                    Transaction(
                        nonces[i], 10**9, 21_000,
                        ADDRS[(i + 1) % 4], 100 + n,
                    ),
                    KEYS[i], chain_id=1,
                )
            )
            nonces[i] += 1
        blocks.append(builder.add_block(txs, coinbase=b"\xaa" * 20))
    return blocks


def _fresh_chain(cfg):
    bc = Blockchain(Storages(), cfg)
    bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
    return bc


def _pipeline_cfg(w=2, depth=2):
    return dataclasses.replace(
        CFG,
        sync=SyncConfig(
            parallel_tx=True, commit_window_blocks=w,
            pipeline_depth=depth,
        ),
    )


# --------------------------------------------------------- ledger core


class TestLedgerCore:
    def test_disabled_transfer_is_inert_singleton(self):
        """The _NULL_SPAN pattern: while disabled, every call site gets
        the SAME inert object — no allocation, no recording."""
        t1 = LEDGER.transfer("x", H2D, 100)
        t2 = LEDGER.transfer("y", D2H, 10**9)
        assert t1 is _NULL_TRANSFER and t2 is _NULL_TRANSFER
        with t1:
            pass
        assert LEDGER.recorded == 0
        assert LEDGER.events() == []

    def test_exact_byte_accounting(self):
        """N events of a known size: totals must be EXACT, not
        approximate — the ledger is an accountant, not a sampler."""
        LEDGER.enable()
        n, size = 64, 576
        for _ in range(n):
            with LEDGER.transfer("fixture.site", H2D, size):
                pass
        LEDGER.record("fixture.site", D2H, 32, duration=0.001)
        totals = LEDGER.totals()
        assert totals[("fixture.site", H2D)]["bytes"] == n * size
        assert totals[("fixture.site", H2D)]["count"] == n
        assert totals[("fixture.site", D2H)]["bytes"] == 32
        assert LEDGER.direction_totals() == {
            H2D: n * size, D2H: 32,
        }

    def test_host_direction_stays_out_of_device_totals(self):
        LEDGER.enable()
        LEDGER.record("window.store", HOST, 4096)
        LEDGER.record("real.site", H2D, 10)
        assert LEDGER.direction_totals() == {H2D: 10, D2H: 0}
        # but the event IS in the ring for classification
        host = [e for e in LEDGER.events() if e.direction == HOST]
        assert len(host) == 1 and host[0].nbytes == 4096

    def test_failed_transfer_not_committed(self):
        LEDGER.enable()
        with pytest.raises(RuntimeError):
            with LEDGER.transfer("x", H2D, 100):
                raise RuntimeError("device fell over")
        assert LEDGER.recorded == 0

    def test_context_tags_and_nesting(self):
        LEDGER.enable()
        with LEDGER.context(window=5, phase="seal"):
            LEDGER.record("a", H2D, 1)
            with LEDGER.context(phase="collect"):
                LEDGER.record("b", D2H, 2)
            LEDGER.record("c", H2D, 3)
        LEDGER.record("d", H2D, 4)
        evs = {e.site: e for e in LEDGER.events()}
        assert (evs["a"].window, evs["a"].phase) == (5, "seal")
        assert (evs["b"].window, evs["b"].phase) == (5, "collect")
        assert (evs["c"].window, evs["c"].phase) == (5, "seal")
        assert (evs["d"].window, evs["d"].phase) == (-1, "")

    def test_window_report_resolution_newest_wins(self):
        """An epoch re-replay reuses block numbers; the report must
        resolve to the NEWEST window covering the block."""
        LEDGER.enable()
        LEDGER.note_window(10, 10, 13)
        with LEDGER.context(window=10, phase="seal"):
            LEDGER.record("old", H2D, 111)
        LEDGER.note_window(12, 12, 15)
        with LEDGER.context(window=12, phase="seal"):
            LEDGER.record("new", H2D, 222)
        rep = LEDGER.window_report(12)
        assert rep["window"] == 12 and rep["blocks"] == 4
        assert "new" in rep["phases"]["seal"]["sites"]
        assert "old" not in rep["phases"]["seal"]["sites"]
        # block 10 is only covered by the first window
        assert LEDGER.window_report(10)["window"] == 10

    def test_window_report_classifies_collect_traffic(self):
        LEDGER.enable()
        LEDGER.note_window(1, 1, 2)
        with LEDGER.context(window=1, phase="collect"):
            LEDGER.record("fused.collect", D2H, 1000)
            LEDGER.record("window.store", HOST, 500)
            LEDGER.record("block.save", HOST, 0, duration=0.01)
        rep = LEDGER.window_report(1)
        cls = rep["collect_classes"]
        assert cls["placeholder-resolution"]["bytes"] == 1000
        assert cls["store-write"]["bytes"] == 500
        assert cls["block-save"]["seconds"] > 0
        # device bytes/block excludes the host events
        assert rep["device_bytes_per_block"] == {D2H: 500}

    def test_ring_overflow_drop_oldest(self):
        led = TransferLedger(capacity=8)
        led.enable()
        for i in range(20):
            led.record(f"s{i}", H2D, i)
        assert led.recorded == 20
        assert led.dropped == 12
        evs = led.events()
        assert len(evs) == 8
        assert evs[0].site == "s12" and evs[-1].site == "s19"

    def test_reset_drops_events_keeps_counters(self):
        """Registry counters are monotonic by contract; reset clears
        the ring and per-block state only."""
        LEDGER.enable()
        LEDGER.record("persist.site", H2D, 100)
        LEDGER.note_blocks(4)
        pair = LEDGER._counters[("persist.site", H2D)]
        before = pair[0].value
        LEDGER.reset()
        assert LEDGER.events() == [] and LEDGER.blocks == 0
        assert LEDGER._counters[("persist.site", H2D)][0].value == before
        LEDGER.record("persist.site", H2D, 50)
        assert pair[0].value == before + 50

    def test_registry_families_and_bytes_per_block_gauge(self):
        from khipu_tpu.observability.registry import REGISTRY

        LEDGER.enable()
        LEDGER.record("gauge.site", H2D, 640)
        LEDGER.note_blocks(2)
        text = REGISTRY.prometheus_text()
        assert text.count(
            "# TYPE khipu_device_transfer_bytes_total counter"
        ) == 1
        assert text.count(
            "# TYPE khipu_device_transfer_seconds_total counter"
        ) == 1
        assert 'site="gauge.site"' in text
        snap = REGISTRY.snapshot()
        gauge = snap.get("khipu_device_transfer_bytes_per_block", {})
        assert gauge.get('direction="h2d"') == 320

    def test_config_enables_ledger(self):
        from khipu_tpu.observability.profiler import apply_config

        apply_config(ObservabilityConfig())  # disabled: no stomp
        assert not LEDGER.enabled
        apply_config(
            ObservabilityConfig(ledger_enabled=True, ledger_capacity=128)
        )
        assert LEDGER.enabled and LEDGER.capacity == 128


# --------------------------------------- exact accounting, device path


@pytest.fixture(scope="module")
def mirror_fixture():
    """N known-size nodes admitted into the real device mirror — the
    fixture the exact-byte tests audit against."""
    n, size = 40, 300
    rng = random.Random(11)
    items = {}
    while len(items) < n:
        enc = rng.randbytes(size)
        items[keccak256(enc)] = enc
    m = DeviceNodeMirror(capacity_rows_per_class=1024)
    m.admit(items)
    m.flush()
    return m, items, size


class TestDeviceByteAccounting:
    def test_mirror_get_exact_bytes(self, mirror_fixture):
        """Each mirror.get fetches one word-major row — exactly
        nwords*4 bytes. The ledger totals must equal calls x row size,
        and agree with the bytes jax.device_get actually moved."""
        import jax
        import numpy as np

        m, items, size = mirror_fixture
        hashes = list(items)[:7]
        measured = []
        real_get = jax.device_get

        def counting_get(x):
            out = real_get(x)
            measured.append(np.asarray(out).nbytes)
            return out

        LEDGER.enable()
        LEDGER.reset()
        try:
            jax.device_get = counting_get
            for h in hashes:
                assert m.get(h) == items[h]
        finally:
            jax.device_get = real_get
        totals = LEDGER.totals()
        got = totals[("mirror.get", D2H)]
        cm = next(iter(m._classes.values()))
        assert got["count"] == len(hashes)
        assert got["bytes"] == len(hashes) * cm.nwords * 4
        # the ledger's claim vs what device_get actually hauled
        assert got["bytes"] == sum(measured)

    def test_mirror_admit_records_h2d(self):
        rng = random.Random(12)
        items = {}
        for _ in range(TILE):  # one full tile: no partial-tile tax
            enc = rng.randbytes(128)
            items[keccak256(enc)] = enc
        LEDGER.enable()
        m = DeviceNodeMirror(capacity_rows_per_class=TILE)
        m.admit(items)
        m.flush()
        totals = LEDGER.totals()
        admit = totals[("mirror.admit", H2D)]
        assert admit["count"] >= 1 and admit["bytes"] > 0
        # a full tile never pays the partial-tile claim round trip
        assert ("mirror.claim", D2H) not in totals


# ------------------------------------------------------- disabled mode


class TestDisabledMode:
    def test_disabled_replay_bit_exact(self):
        """Ledger on vs off: byte-identical chain heads (replay
        validates every window root, so any instrumentation-induced
        divergence would raise long before this assert)."""
        chain = _chain(8, 8)
        cfg = _pipeline_cfg()
        bc_off = _fresh_chain(cfg)
        ReplayDriver(bc_off, cfg).replay(chain)
        LEDGER.enable()
        bc_on = _fresh_chain(cfg)
        ReplayDriver(bc_on, cfg).replay(chain)
        LEDGER.disable()
        h_off = bc_off.get_header_by_number(8)
        h_on = bc_on.get_header_by_number(8)
        assert h_off.hash == h_on.hash == chain[-1].hash
        assert h_off.state_root == h_on.state_root

    def test_no_extra_device_syncs(self, mirror_fixture):
        """Enabling the ledger must not change HOW MANY device syncs a
        workload performs — nbytes comes from host-side attribute loads
        (arr.nbytes / precomputed sizes), never a device_get."""
        import jax

        m, items, _size = mirror_fixture
        hashes = list(items)[:5]
        counts = []
        real_get = jax.device_get

        def run():
            calls = [0]

            def counting_get(x):
                calls[0] += 1
                return real_get(x)

            jax.device_get = counting_get
            try:
                for h in hashes:
                    m.get(h)
                assert m.verify() == 0
            finally:
                jax.device_get = real_get
            counts.append(calls[0])

        run()  # disabled
        LEDGER.enable()
        run()  # enabled
        LEDGER.disable()
        assert counts[0] == counts[1] and counts[0] > 0


# ------------------------------------------------------ counter tracks


class TestCounterTracks:
    def _synthetic_ledger(self):
        LEDGER.enable()
        with LEDGER.context(window=1, phase="seal"):
            for i in range(3):
                LEDGER.record("fused.dispatch", H2D, 1000 * (i + 1),
                              duration=0.01)
        with LEDGER.context(window=1, phase="collect"):
            LEDGER.record("fused.collect", D2H, 512, duration=0.02)
            LEDGER.record("window.store", HOST, 4096, duration=0.001)

    def test_counter_tracks_valid_chrome_json(self):
        self._synthetic_ledger()
        doc = export.chrome_trace(spans=[])
        text = json.dumps(doc)  # must be JSON-serializable
        doc2 = json.loads(text)
        counters = [
            e for e in doc2["traceEvents"] if e.get("ph") == "C"
        ]
        names = {e["name"] for e in counters}
        assert "transfer bytes in flight" in names
        assert "transfer bytes (cumulative)" in names
        for e in counters:
            assert isinstance(e["ts"], (int, float))
            assert all(
                isinstance(v, (int, float)) for v in e["args"].values()
            )

    def test_in_flight_track_sums_to_zero(self):
        """Every +start edge has a matching -end edge: the last
        in-flight sample must be 0 on every direction."""
        self._synthetic_ledger()
        events = export.counter_tracks()
        flight = [
            e for e in events if e["name"] == "transfer bytes in flight"
        ]
        assert flight, "no in-flight samples"
        assert all(v == 0 for v in flight[-1]["args"].values())
        # host events never enter the in-flight track
        assert all(
            "host" not in e["args"] for e in flight
        )

    def test_cumulative_track_is_monotone_per_phase(self):
        self._synthetic_ledger()
        events = export.counter_tracks()
        cum = [
            e for e in events
            if e["name"] == "transfer bytes (cumulative)"
        ]
        last = {}
        for e in cum:
            for phase, v in e["args"].items():
                assert v >= last.get(phase, 0)
                last[phase] = v
        assert last.get("seal") == 6000
        assert last.get("collect") == 512

    def test_empty_ledger_adds_no_counter_events(self):
        assert export.counter_tracks() == []


# ------------------------------------------------------- window report


class TestWindowReportRPC:
    def test_not_found_shape(self):
        rep = recorder.window_report(999)
        assert rep == {
            "found": False, "number": 999, "ledgerEnabled": False,
        }

    def test_report_through_replay(self):
        """End-to-end: a pipelined replay with the ledger on produces a
        per-window phase x site record with store-write and block-save
        classification (host-hasher path: host-side classes only)."""
        chain = _chain(8, 8)
        cfg = _pipeline_cfg(w=2, depth=2)
        LEDGER.enable()
        ReplayDriver(_fresh_chain(cfg), cfg).replay(chain)
        LEDGER.disable()
        rep = recorder.window_report(3)
        assert rep["found"]
        assert rep["block_lo"] <= 3 <= rep["block_hi"]
        # host-hasher path: seal dispatches nothing to a device and
        # rootchecks resolve from the in-host mapping, so the ledger
        # events land in the spill (persist) and block-save (save)
        # stages of the staged collector
        assert {"persist", "save"} <= set(rep["phases"])
        cls = rep["collect_classes"]
        assert cls["store-write"]["bytes"] > 0
        assert cls["block-save"]["seconds"] > 0


# ------------------------------------------------------- compare gate


class TestCompareGate:
    @staticmethod
    def _bench():
        import os
        import sys

        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        import bench

        return bench

    def _tiny_runner(self, bench):
        # 12x8 rather than the original 4x4: the 4x4 fixture's phase
        # totals are single-digit milliseconds, where 1 ms of scheduler
        # jitter on a loaded box reads as a ~0.12 collect-share swing —
        # flaking the honest self-compare against the 0.15 share gate
        def run():
            bench.bench_replay(
                12, 8, "replay_parallel_commit_fixture_blocks_per_sec",
                parallel=True, window=2,
            )
        return run

    def _baseline_doc(self, lines):
        return {
            "n": 1, "cmd": "test", "rc": 0,
            "tail": "\n".join(json.dumps(x) for x in lines),
        }

    def test_parse_baseline_tolerates_truncated_lines(self, tmp_path):
        bench = self._bench()
        p = tmp_path / "base.json"
        doc = self._baseline_doc([{"metric": "ok", "value": 1}])
        # prepend a truncated fragment, the BENCH_r05 shape
        doc["tail"] = 'runcated_fragment": 1}\n' + doc["tail"]
        p.write_text(json.dumps(doc))
        base = bench.parse_baseline(str(p))
        assert base == {"ok": {"metric": "ok", "value": 1}}

    def test_real_baseline_parses(self):
        bench = self._bench()
        base = bench.parse_baseline("BENCH_r05.json")
        assert "replay_contended_erc20_blocks_per_sec" in base
        assert (
            "keccak256_576B_trie_node_hashes_per_sec_per_chip" in base
        )

    def test_honest_run_exits_zero(self, tmp_path):
        bench = self._bench()
        run = self._tiny_runner(bench)
        # capture the tiny config's own output as its baseline: an
        # honest re-run of the same code cannot regress against itself
        mark = len(bench._EMITTED)
        run()
        line = bench._EMITTED[mark]
        p = tmp_path / "honest.json"
        p.write_text(json.dumps(self._baseline_doc([line])))
        assert bench.bench_compare(str(p), runners=[run]) == 0

    def test_doctored_baseline_trips_nonzero(self, tmp_path):
        bench = self._bench()
        run = self._tiny_runner(bench)
        doctored = {
            "metric": "replay_parallel_commit_fixture_blocks_per_sec",
            "value": 10**9, "unit": "blocks/s",
        }
        p = tmp_path / "doctored.json"
        p.write_text(json.dumps(self._baseline_doc([doctored])))
        assert bench.bench_compare(str(p), runners=[run]) == 1
        # the gate line names the failure
        gate = bench._EMITTED[-1]
        assert gate["metric"] == "bench_compare"
        assert gate["value"] == 1 and gate["failed"]

    def test_collect_share_regression_trips(self, tmp_path):
        bench = self._bench()
        run = self._tiny_runner(bench)
        mark = len(bench._EMITTED)
        run()
        line = dict(bench._EMITTED[mark])
        # doctor the BASELINE's phase split: collect share near zero,
        # so the honest re-run's real share reads as a regression
        phases = {k: 0.0 for k in line.get("phases", {})}
        phases["execute"] = 10.0
        line["phases"] = phases
        p = tmp_path / "share.json"
        p.write_text(json.dumps(self._baseline_doc([line])))
        rc = bench.bench_compare(
            str(p), runners=[run],
            thresholds={"max_collect_share_delta": 0.01},
        )
        assert rc == 1


# ----------------------------------------------- registry satellites


class TestRegistryScrapePass:
    def test_collector_pulled_once_per_pass(self):
        reg = MetricsRegistry()
        pulls = [0]

        def collector():
            pulls[0] += 1
            return [("khipu_test_gauge", "gauge", {}, 7)]

        reg.register_collector("t", collector)
        # one exposition pass = one pull, however many families read it
        text = reg.prometheus_text()
        assert "khipu_test_gauge 7" in text
        assert pulls[0] == 1
        reg.snapshot()
        assert pulls[0] == 2
        assert reg.collector_pulls == 2

    def test_scrape_pass_caches_and_restores(self):
        reg = MetricsRegistry()
        pulls = [0]
        reg.register_collector(
            "t", lambda: (
                pulls.__setitem__(0, pulls[0] + 1)
                or [("khipu_x", "gauge", {}, pulls[0])]
            )
        )
        with reg.scrape_pass():
            reg.snapshot()
            reg.prometheus_text()
            reg.snapshot()
        assert pulls[0] == 1, "one pull per pass, however many reads"
        reg.snapshot()  # pass closed: fresh pull
        assert pulls[0] == 2

    def test_histogram_bucket_override(self):
        reg = MetricsRegistry()
        h = reg.histogram("khipu_h", buckets=(0.1, 1.0))
        assert h.buckets == (0.1, 1.0)
        # re-register with different buckets before any observation:
        # override applies
        h2 = reg.histogram("khipu_h", buckets=(0.5, 2.0, 8.0))
        assert h2 is h and h.buckets == (0.5, 2.0, 8.0)
        h.observe(0.7)
        # after the first observation the shape is frozen
        reg.histogram("khipu_h", buckets=(9.0,))
        assert h.buckets == (0.5, 2.0, 8.0)
        text = reg.prometheus_text()
        assert 'le="2.0"' in text and 'le="+Inf"' in text


# ------------------------------------------------ sampling satellite


class TestTraceSampling:
    def test_trace_sampled_deterministic(self):
        tid = "00deadbeef"
        expect = int(tid, 16) % 10_000 < 250
        assert trace_sampled(tid, 250) == expect
        # same id, same answer, every process (no PYTHONHASHSEED)
        assert trace_sampled(tid, 250) == trace_sampled(tid, 250)
        assert trace_sampled(tid, 10_000) is True
        assert trace_sampled(tid, 0) is False
        assert trace_sampled("not-hex", 1) is True  # foreign id: keep

    def test_rate_distribution_rough(self):
        ids = [
            "%032x" % random.Random(i).getrandbits(128)
            for i in range(400)
        ]
        kept = sum(trace_sampled(t, 5000) for t in ids)
        assert 120 <= kept <= 280  # ~50% with slack

    def test_set_sample_rate_gates_enabled(self):
        t = tracer
        assert not t.enabled
        try:
            t.enable()
            t.set_sample_rate(10_000)
            assert t.enabled
            t.set_sample_rate(0)
            assert not t.enabled and t._on and not t.sampled
            t.set_sample_rate(10_000)
            assert t.enabled
        finally:
            t.disable()
            t.set_sample_rate(10_000)
            t.reset()

    def test_unsampled_tracer_records_nothing(self):
        t = tracer
        try:
            t.enable()
            t.set_sample_rate(0)
            with t.span("should.not.record"):
                pass
            assert t.recorded == 0
        finally:
            t.disable()
            t.set_sample_rate(10_000)
            t.reset()

    def test_apply_config_sets_rate(self):
        from khipu_tpu.observability.trace import apply_config

        t = tracer
        try:
            apply_config(
                ObservabilityConfig(enabled=True, sample_per_10k=7)
            )
            assert t._on and t.sample_per_10k == 7
            assert t.enabled == trace_sampled(t.trace_id, 7)
        finally:
            t.disable()
            t.set_sample_rate(10_000)
            t.reset()
