"""Domain-type tests: hash identities against published Ethereum
vectors (SURVEY.md §4 plan item 1; parity targets domain/*.scala)."""

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.domain.account import (
    EMPTY_CODE_HASH,
    EMPTY_STORAGE_ROOT,
    Account,
)
from khipu_tpu.domain.block import Block, BlockBody
from khipu_tpu.domain.block_header import EMPTY_OMMERS_HASH, BlockHeader
from khipu_tpu.domain.receipt import (
    Receipt,
    TxLogEntry,
    decode_receipts,
    encode_receipts,
)
from khipu_tpu.domain.transaction import (
    SignedTransaction,
    Transaction,
    contract_address,
    create2_address,
    sign_transaction,
)
from khipu_tpu.trie.mpt import EMPTY_TRIE_HASH

# The published mainnet genesis state root (tests/test_trie.py builds it
# from the alloc fixture) and block hash.
MAINNET_GENESIS_STATE_ROOT = bytes.fromhex(
    "d7f8974fb5ac78d9ac099b9ad5018bedc2ce0a72dad1827a1709da30580f0544"
)
MAINNET_GENESIS_HASH = bytes.fromhex(
    "d4e56740f876aef8c010b86a40d5f56745a118d0906a34e69aec8c0db1cb8fa3"
)


class TestBlockHeader:
    def mainnet_genesis_header(self):
        return BlockHeader(
            parent_hash=b"\x00" * 32,
            ommers_hash=EMPTY_OMMERS_HASH,
            beneficiary=b"\x00" * 20,
            state_root=MAINNET_GENESIS_STATE_ROOT,
            transactions_root=EMPTY_TRIE_HASH,
            receipts_root=EMPTY_TRIE_HASH,
            logs_bloom=b"\x00" * 256,
            difficulty=0x400000000,
            number=0,
            gas_limit=0x1388,
            gas_used=0,
            unix_timestamp=0,
            extra_data=bytes.fromhex(
                "11bbe8db4e347b4e8c937c1c8370e4b5"
                "ed33adb3db69cbdb7a38e1e50b1b82fa"
            ),
            mix_hash=b"\x00" * 32,
            nonce=bytes.fromhex("0000000000000042"),
        )

    def test_mainnet_genesis_hash(self):
        """hash = kec256(rlp(header)) reproduces the published mainnet
        genesis block hash — the full 15-field RLP identity."""
        assert self.mainnet_genesis_header().hash == MAINNET_GENESIS_HASH

    def test_decode_roundtrip(self):
        h = self.mainnet_genesis_header()
        assert BlockHeader.decode(h.encode()) == h


class TestTransaction:
    def test_eip155_sender_recovery(self):
        """The EIP-155 example: priv 0x46..46 -> published sender."""
        tx = Transaction(
            nonce=9,
            gas_price=20 * 10**9,
            gas_limit=21000,
            to=bytes.fromhex("3535353535353535353535353535353535353535"),
            value=10**18,
        )
        stx = sign_transaction(tx, b"\x46" * 32, chain_id=1)
        assert stx.v == 37
        assert stx.sender == pubkey_to_address(
            privkey_to_pubkey(b"\x46" * 32)
        )
        assert stx.chain_id == 1

    def test_decode_roundtrip_and_hash_stability(self):
        tx = Transaction(3, 10**9, 50_000, None, 7, b"\x60\x00")
        stx = sign_transaction(tx, b"\x01".rjust(32, b"\x00"), chain_id=5)
        again = SignedTransaction.decode(stx.encode())
        assert again == stx
        assert again.hash == stx.hash
        assert again.sender == stx.sender

    def test_pre_eip155_signature(self):
        tx = Transaction(0, 1, 21000, b"\x11" * 20, 5)
        stx = sign_transaction(tx, b"\x02".rjust(32, b"\x00"))
        assert stx.v in (27, 28)
        assert stx.chain_id is None
        assert stx.sender == pubkey_to_address(
            privkey_to_pubkey(b"\x02".rjust(32, b"\x00"))
        )

    def test_tampered_signature_changes_sender(self):
        tx = Transaction(0, 1, 21000, b"\x11" * 20, 5)
        stx = sign_transaction(tx, b"\x02".rjust(32, b"\x00"))
        bad = SignedTransaction(tx, stx.v, stx.r, stx.s ^ 1)
        assert bad.sender != stx.sender

    def test_contract_addresses(self):
        sender = bytes.fromhex("6ac7ea33f8831ea9dcc53393aaa88b25a785dbf0")
        # cow's first contract address (well-known vector)
        assert contract_address(sender, 0) == bytes.fromhex(
            "cd234a471b72ba2f1ccf0a70fcaba648a5eecd8d"
        )
        # EIP-1014 example 1: sender 0x0, salt 0, code 0x00
        assert create2_address(
            b"\x00" * 20, b"\x00" * 32, b"\x00"
        ) == bytes.fromhex("4d1a2e2bb4f88f0250f26ffff098b0b30b26bf38")


class TestAccountAndReceipts:
    def test_fresh_account_encoding(self):
        acc = Account()
        assert acc.storage_root == EMPTY_STORAGE_ROOT
        assert acc.code_hash == EMPTY_CODE_HASH
        assert Account.decode(acc.encode()) == acc
        assert acc.is_empty

    def test_account_roundtrip(self):
        acc = Account(5, 10**20, b"\x11" * 32, b"\x22" * 32)
        assert Account.decode(acc.encode()) == acc
        assert not acc.is_empty

    def test_receipt_roundtrip_status_and_root(self):
        log = TxLogEntry(b"\xaa" * 20, (b"\x01" * 32, b"\x02" * 32), b"xy")
        for post in (1, 0, b"\x33" * 32):
            r = Receipt(post, 21_000, b"\x00" * 256, (log,))
            assert Receipt.decode(r.encode()) == r

    def test_receipts_list_codec(self):
        rs = [
            Receipt(1, 21000, b"\x00" * 256),
            Receipt(0, 42000, b"\x00" * 256),
        ]
        assert decode_receipts(encode_receipts(rs)) == rs


class TestBlock:
    def test_block_codec_roundtrip(self):
        tx = sign_transaction(
            Transaction(0, 1, 21000, b"\x11" * 20, 5),
            b"\x03".rjust(32, b"\x00"),
            chain_id=1,
        )
        header = TestBlockHeader().mainnet_genesis_header()
        block = Block(header, BlockBody((tx,), (header,)))
        assert Block.decode(block.encode()) == block
        body = BlockBody((tx,), ())
        assert BlockBody.decode(body.encode()) == body
