"""Sharded node-cache cluster (khipu_tpu/cluster/): ring placement,
replica failover, breakers, health membership, and the 3-shard
kill-one-shard loopback integration (P6 DistributedNodeStorage role
scaled out — ISSUE 1 acceptance)."""

import collections
import os
import signal
import subprocess
import sys
import time

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.cluster import (
    CircuitBreaker,
    HashRing,
    HealthMonitor,
    ShardedNodeClient,
)
from khipu_tpu.cluster.client import CLOSED, HALF_OPEN, OPEN


def _key(i: int) -> bytes:
    return keccak256(i.to_bytes(4, "big"))


# --------------------------------------------------------------- ring


class TestHashRing:
    def test_distribution_uniformity_bounds(self):
        ring = HashRing(["a:1", "b:2", "c:3"], replication=2, vnodes=128)
        counts = collections.Counter(
            ring.primary_for(_key(i)) for i in range(6000)
        )
        assert set(counts) == {"a:1", "b:2", "c:3"}
        for ep, n in counts.items():
            share = n / 6000
            # 128 vnodes keeps shares near 1/3; wide bounds so the
            # test pins the property, not the exact hash layout
            assert 0.15 < share < 0.55, (ep, share)

    def test_replicas_distinct_and_sized(self):
        ring = HashRing(["a", "b", "c", "d"], replication=3)
        for i in range(200):
            reps = ring.replicas_for(_key(i))
            assert len(reps) == 3
            assert len(set(reps)) == 3

    def test_replication_capped_by_membership(self):
        ring = HashRing(["only"], replication=3)
        assert ring.replicas_for(_key(1)) == ["only"]
        assert HashRing([], replication=2).replicas_for(_key(1)) == []

    def test_placement_deterministic_across_instances(self):
        a = HashRing(["x", "y", "z"], replication=2)
        b = HashRing(["z", "x", "y"], replication=2)  # order-insensitive
        for i in range(300):
            assert a.replicas_for(_key(i)) == b.replicas_for(_key(i))

    def test_remove_moves_only_dead_shards_keys(self):
        ring = HashRing(["a", "b", "c"], replication=1, vnodes=128)
        before = {_key(i): ring.primary_for(_key(i)) for i in range(800)}
        ring.remove("b")
        for k, owner in before.items():
            if owner != "b":
                # consistent hashing: surviving owners keep their keys
                assert ring.primary_for(k) == owner
            else:
                assert ring.primary_for(k) in ("a", "c")
        ring.add("b")
        for k, owner in before.items():
            assert ring.primary_for(k) == owner  # rejoin restores

    def test_add_remove_report_change(self):
        ring = HashRing(["a"], replication=1)
        assert ring.add("b") is True
        assert ring.add("b") is False
        assert ring.remove("b") is True
        assert ring.remove("b") is False


# ------------------------------------------------------------ breaker


class TestCircuitBreaker:
    def test_open_half_open_close_transitions(self):
        now = [0.0]
        br = CircuitBreaker(
            failure_threshold=3, reset_timeout=10.0, clock=lambda: now[0]
        )
        assert br.state == CLOSED and br.allow()
        for _ in range(3):
            br.record_failure()
        assert br.state == OPEN
        assert not br.allow()
        now[0] = 9.9
        assert not br.allow()
        now[0] = 10.1
        assert br.state == HALF_OPEN
        assert br.allow()  # exactly one probe
        assert not br.allow()  # concurrent call still shut out
        br.record_success()
        assert br.state == CLOSED and br.allow()

    def test_failed_probe_rearms_full_window(self):
        now = [0.0]
        br = CircuitBreaker(
            failure_threshold=2, reset_timeout=5.0, clock=lambda: now[0]
        )
        br.record_failure()
        br.record_failure()
        now[0] = 5.5
        assert br.allow()  # half-open probe
        br.record_failure()  # probe failed
        assert br.state == OPEN
        assert not br.allow()
        now[0] = 10.4
        assert not br.allow()  # window restarted at t=5.5
        now[0] = 10.6
        # 5.5 + 5.0 = 10.5 -> half-open again
        assert br.state == HALF_OPEN
        assert br.allow()

    def test_success_resets_failure_streak(self):
        br = CircuitBreaker(failure_threshold=3, clock=lambda: 0.0)
        br.record_failure()
        br.record_failure()
        br.record_success()
        br.record_failure()
        br.record_failure()
        assert br.state == CLOSED  # streak broken, never reached 3


# ------------------------------------------- fake-transport client


class FakeShard:
    """In-memory stand-in for BridgeClient with scripted failures."""

    def __init__(self, store=None, fail=False):
        self.store = dict(store or {})
        self.fail = fail
        self.get_calls = 0
        self.put_calls = 0

    def get_node_data(self, hashes):
        self.get_calls += 1
        if self.fail:
            raise ConnectionError("shard down")
        return {h: self.store[h] for h in hashes if h in self.store}

    def put_node_data(self, nodes):
        self.put_calls += 1
        if self.fail:
            raise ConnectionError("shard down")
        self.store.update(nodes)
        return len(nodes)

    def ping(self, payload=b""):
        if self.fail:
            raise ConnectionError("shard down")
        return payload

    def close(self):
        pass


def make_client(shards, **kwargs):
    kwargs.setdefault("replication", 2)
    kwargs.setdefault("max_retries", 1)
    kwargs.setdefault("sleep", lambda s: None)  # no real backoff waits
    return ShardedNodeClient(
        list(shards),
        channel_factory=lambda ep: shards[ep],
        **kwargs,
    )


VAL = b"some mpt node rlp bytes"
KEY = keccak256(VAL)


class TestShardedNodeClient:
    def test_fetch_verified_and_counted(self):
        shards = {ep: FakeShard({KEY: VAL}) for ep in ("a", "b", "c")}
        cl = make_client(shards)
        assert cl.fetch([KEY, KEY]) == {KEY: VAL}  # dedup too
        prim = cl.ring.replicas_for(KEY)[0]
        assert cl.metrics[prim].served == 1
        snap = cl.metrics_snapshot()
        assert snap["shards"][prim]["hitRate"] == 1.0
        assert snap["replication"] == 2

    def test_replica_fallback_ordering(self):
        shards = {ep: FakeShard({KEY: VAL}) for ep in ("a", "b", "c")}
        cl = make_client(shards)
        chain = cl.ring.replicas_for(KEY)
        shards[chain[0]].fail = True  # kill the primary
        assert cl.fetch([KEY]) == {KEY: VAL}
        # the PRIMARY was attempted (and failed) before the replica
        assert cl.metrics[chain[0]].failures > 0
        assert cl.metrics[chain[1]].served == 1
        assert cl.metrics[chain[1]].failovers == 1

    def test_corrupt_replica_never_serves_wrong_bytes(self):
        shards = {ep: FakeShard({KEY: VAL}) for ep in ("a", "b", "c")}
        cl = make_client(shards)
        chain = cl.ring.replicas_for(KEY)
        shards[chain[0]].store[KEY] = b"evil bytes"  # wrong content
        out = cl.fetch([KEY])
        assert out == {KEY: VAL}  # healed from the honest replica
        assert cl.metrics[chain[0]].corrupt == 1

    def test_local_fallback_when_all_replicas_down(self):
        shards = {ep: FakeShard(fail=True) for ep in ("a", "b")}
        local = {KEY: VAL}
        cl = make_client(shards, local_get=local.get)
        assert cl.fetch([KEY]) == {KEY: VAL}
        assert cl.local_fallbacks == 1

    def test_unreachable_counted_not_fabricated(self):
        shards = {ep: FakeShard(fail=True) for ep in ("a", "b")}
        cl = make_client(shards)
        assert cl.fetch([KEY]) == {}
        assert cl.unreachable == 1

    def test_retry_then_success(self):
        class FlakyShard(FakeShard):
            def get_node_data(self, hashes):
                self.get_calls += 1
                if self.get_calls == 1:
                    raise ConnectionError("transient")
                return super().get_node_data(hashes)

        shards = {"a": FlakyShard({KEY: VAL})}
        cl = make_client(shards, replication=1, max_retries=2)
        assert cl.fetch([KEY]) == {KEY: VAL}
        assert cl.metrics["a"].failures == 1
        assert cl.metrics["a"].served == 1

    def test_breaker_shields_dead_shard(self):
        shards = {ep: FakeShard(fail=True) for ep in ("a", "b")}
        local = {KEY: VAL}
        cl = make_client(
            shards, local_get=local.get,
            breaker_failures=2, max_retries=0,
        )
        for _ in range(4):
            cl.fetch([KEY])
        # after the breaker opened, the dead shard stops being dialed
        assert shards["a"].get_calls <= 2
        assert shards["b"].get_calls <= 2
        assert cl.breakers["a"].state == OPEN

    def test_write_replication_places_on_replica_set(self):
        shards = {ep: FakeShard() for ep in ("a", "b", "c")}
        cl = make_client(shards)
        placed = cl.replicate({KEY: VAL})
        assert placed == 2  # replication factor
        holders = [ep for ep, sh in shards.items() if KEY in sh.store]
        assert sorted(holders) == sorted(cl.ring.replicas_for(KEY))

    def test_replicated_key_survives_primary_death(self):
        shards = {ep: FakeShard() for ep in ("a", "b", "c")}
        cl = make_client(shards)
        cl.replicate({KEY: VAL})
        chain = cl.ring.replicas_for(KEY)
        shards[chain[0]].fail = True  # SIGKILL-equivalent on the fake
        assert cl.fetch([KEY]) == {KEY: VAL}

    def test_mark_dead_rebalances_new_reads(self):
        shards = {ep: FakeShard({KEY: VAL}) for ep in ("a", "b", "c")}
        cl = make_client(shards)
        chain = cl.ring.replicas_for(KEY)
        cl.mark_dead(chain[0])
        assert chain[0] not in cl.ring.members
        new_chain = cl.ring.replicas_for(KEY)
        assert chain[0] not in new_chain
        assert cl.fetch([KEY]) == {KEY: VAL}
        assert shards[chain[0]].get_calls == 0  # never dialed
        cl.mark_alive(chain[0])
        assert cl.ring.replicas_for(KEY) == chain


# ------------------------------------- backoff jitter determinism


class TestBackoffJitterDeterminism:
    """KL003 fix (docs/static_analysis.md): retry jitter draws from a
    per-client ``random.Random(jitter_seed)`` (ClusterConfig.jitter_seed),
    never the process-global random module — so a seeded chaos run
    replays the identical backoff schedule, and nothing else seeding
    the global RNG can perturb it."""

    @staticmethod
    def _backoff_schedule(seed, global_seed):
        import random as _random

        # perturb the GLOBAL rng differently per call: a client leaking
        # to module-level random.random() would make same-seed runs
        # diverge and fail the replay assertion below
        _random.seed(global_seed)
        shards = {ep: FakeShard(fail=True) for ep in ("a", "b")}
        slept = []
        cl = make_client(
            shards, max_retries=3, breaker_failures=100,
            sleep=slept.append, jitter_seed=seed,
        )
        cl.fetch([KEY])
        return slept

    def test_same_seed_replays_identical_schedule(self):
        first = self._backoff_schedule(7, global_seed=1)
        second = self._backoff_schedule(7, global_seed=2)
        assert first, "failing fetch must have slept between retries"
        assert first == second

    def test_different_seeds_decorrelate(self):
        a = self._backoff_schedule(7, global_seed=1)
        b = self._backoff_schedule(8, global_seed=1)
        assert a != b


# ------------------------------------------------------------- health


class TestHealthMonitor:
    def test_down_and_up_with_hysteresis(self):
        shards = {ep: FakeShard({KEY: VAL}) for ep in ("a", "b", "c")}
        cl = make_client(shards)
        mon = HealthMonitor(cl, down_after=2, up_after=1)
        shards["b"].fail = True
        mon.probe_once()
        assert mon.alive("b")  # one miss is not a verdict
        mon.probe_once()
        assert not mon.alive("b")
        assert "b" not in cl.ring.members
        assert mon.transitions == 1
        shards["b"].fail = False
        mon.probe_once()
        assert mon.alive("b")
        assert "b" in cl.ring.members
        assert mon.transitions == 2

    def test_probe_loop_runs_in_background(self):
        shards = {"a": FakeShard()}
        cl = make_client(shards, replication=1)
        mon = HealthMonitor(cl, interval=0.01)
        mon.start()
        try:
            deadline = time.time() + 2
            while mon._hits.get("a", 0) == 0 and time.time() < deadline:
                time.sleep(0.01)
            assert mon._hits.get("a", 0) > 0
        finally:
            mon.stop()


# ------------------------------------- read-through + metrics glue


class TestReadThroughIntegration:
    def test_from_cluster_heals_and_replicates(self):
        from khipu_tpu.storage.datasource import MemoryKeyValueDataSource
        from khipu_tpu.storage.node_storage import NodeStorage
        from khipu_tpu.storage.remote import RemoteReadThroughNodeStorage

        shards = {ep: FakeShard({KEY: VAL}) for ep in ("a", "b", "c")}
        cl = make_client(shards)
        store = RemoteReadThroughNodeStorage.from_cluster(
            NodeStorage(MemoryKeyValueDataSource()), cl,
            replicate_writes=True,
        )
        assert store.get(KEY) == VAL  # healed through the cluster
        assert store.healed == 1
        other = b"another node"
        store.put(keccak256(other), other)  # write side replicates
        holders = [
            ep for ep, sh in shards.items() if keccak256(other) in sh.store
        ]
        assert len(holders) == 2

    def test_khipu_metrics_surfaces_cluster(self):
        from khipu_tpu.config import fixture_config
        from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
        from khipu_tpu.jsonrpc.eth_service import EthService
        from khipu_tpu.storage.storages import Storages

        shards = {ep: FakeShard({KEY: VAL}) for ep in ("a", "b")}
        cl = make_client(shards)
        chain = cl.ring.replicas_for(KEY)
        shards[chain[0]].fail = True
        cl.fetch([KEY])  # force a failover so the counter moves
        cfg = fixture_config(chain_id=1)
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec())
        svc = EthService(bc, cfg, cluster=cl)
        m = svc.khipu_metrics()
        assert "cluster" in m
        shards_m = m["cluster"]["shards"]
        assert shards_m[chain[1]]["failovers"] == 1
        assert shards_m[chain[0]]["breakerState"] in (CLOSED, OPEN)
        assert shards_m[chain[1]]["served"] == 1


# --------------------------------------- 3-shard loopback kill test

SHARD_SCRIPT = r"""
import sys
sys.path.insert(0, {repo!r})
from khipu_tpu.config import fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.base.crypto.secp256k1 import privkey_to_pubkey, pubkey_to_address
from khipu_tpu.bridge import BridgeServer

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(3)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ALLOC = {{a: 10**21 for a in ADDRS}}
bc = Blockchain(Storages(), CFG)
builder = ChainBuilder(bc, CFG, GenesisSpec(alloc=ALLOC))
for i in range(4):
    builder.add_block(
        [sign_transaction(Transaction(i, 10**9, 21000, ADDRS[1], 5),
                          KEYS[0], chain_id=1)],
        coinbase=b"\xaa" * 20,
    )
server = BridgeServer(bc, CFG)
port = server.start()
root = bc.get_header_by_number(4).state_root
print(f"{{port}} {{root.hex()}}", flush=True)
sys.stdin.readline()  # parent closes stdin to stop us
"""


class TestThreeShardKillOne:
    """ISSUE 1 acceptance: 3 bridge shards over identical populated
    stores; one SIGKILLed mid-run; reads keep healing via replicas
    (hash-verified — the client never admits wrong bytes), and the
    failover counters are visible through khipu_metrics."""

    def _spawn_shards(self, n=3):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", SHARD_SCRIPT.format(repo=repo)],
                stdout=subprocess.PIPE,
                stdin=subprocess.PIPE,
                text=True,
            )
            for _ in range(n)
        ]
        endpoints, roots = [], []
        for p in procs:
            port, root = p.stdout.readline().split()
            endpoints.append(f"127.0.0.1:{int(port)}")
            roots.append(bytes.fromhex(root))
        assert len(set(roots)) == 1, "shards must agree on state"
        return procs, endpoints, roots[0]

    def test_reads_heal_across_a_shard_kill(self):
        pytest.importorskip("grpc")
        from khipu_tpu.base.crypto.secp256k1 import (
            privkey_to_pubkey,
            pubkey_to_address,
        )
        from khipu_tpu.config import fixture_config
        from khipu_tpu.domain.account import Account, address_key
        from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
        from khipu_tpu.jsonrpc.eth_service import EthService
        from khipu_tpu.storage.datasource import MemoryKeyValueDataSource
        from khipu_tpu.storage.node_storage import NodeStorage
        from khipu_tpu.storage.remote import RemoteReadThroughNodeStorage
        from khipu_tpu.storage.storages import Storages
        from khipu_tpu.trie.mpt import MerklePatriciaTrie

        keys = [(i + 1).to_bytes(32, "big") for i in range(3)]
        addrs = [pubkey_to_address(privkey_to_pubkey(k)) for k in keys]
        procs, endpoints, root = self._spawn_shards(3)
        killed = None
        try:
            client = ShardedNodeClient(
                endpoints,
                replication=2,
                max_retries=1,
                backoff_base=0.01,
                breaker_failures=2,
                breaker_reset=30.0,
            )
            mon = HealthMonitor(client, down_after=1)

            def fresh_trie():
                # empty local store per walk: every node must heal
                # through the cluster, hash-verified by the client
                local = RemoteReadThroughNodeStorage.from_cluster(
                    NodeStorage(MemoryKeyValueDataSource()), client
                )
                return local, MerklePatriciaTrie(local, root_hash=root)

            local, trie = fresh_trie()
            raw = trie.get(address_key(addrs[1]))
            assert raw is not None
            assert Account.decode(raw).balance == 10**21 + 4 * 5
            assert local.healed > 0

            # replicate an out-of-band node, then SIGKILL one of its
            # replicas mid-run: the write-replicated copy must survive
            extra = b"replicated-out-of-band-node"
            extra_key = keccak256(extra)
            assert client.replicate({extra_key: extra}) == 2
            victim_ep = client.ring.replicas_for(extra_key)[0]
            victim = procs[endpoints.index(victim_ep)]
            victim.kill()  # SIGKILL, no graceful stop
            victim.wait(timeout=10)
            killed = victim

            # reads keep healing through surviving replicas
            local, trie = fresh_trie()
            raw = trie.get(address_key(addrs[0]))
            assert raw is not None
            acc = Account.decode(raw)
            assert acc.balance == 10**21 - 4 * 5 - 4 * 21000 * 10**9
            assert acc.nonce == 4

            # the write-replicated node survives its primary's death
            assert client.fetch([extra_key]) == {extra_key: extra}

            # health probe takes the corpse out of the ring
            mon.probe_once()
            assert victim_ep not in client.ring.members
            local, trie = fresh_trie()
            assert trie.get(address_key(addrs[2])) is not None

            # failover counters visible through the metrics RPC
            cfg = fixture_config(chain_id=1)
            bc = Blockchain(Storages(), cfg)
            bc.load_genesis(GenesisSpec())
            m = EthService(bc, cfg, cluster=client).khipu_metrics()
            shard_m = m["cluster"]["shards"]
            assert victim_ep in shard_m
            assert shard_m[victim_ep]["failures"] > 0
            assert (
                sum(s["failovers"] for s in shard_m.values()) > 0
            )
            assert m["cluster"]["unreachable"] == 0  # zero lost reads
            total_served = sum(s["served"] for s in shard_m.values())
            assert total_served >= local.healed
            client.close()
        finally:
            for p in procs:
                if p is not killed:
                    try:
                        p.stdin.close()
                        p.wait(timeout=10)
                    except Exception:
                        p.kill()
