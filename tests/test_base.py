"""Unit tests for L0 primitives: RLP, hex-prefix, Keccak.

Vector sources: Ethereum Yellow Paper appendix B examples and the
Keccak reference digests (also exercised by the reference's
crypto/package.scala kec256 call sites).
"""

import pytest

from khipu_tpu.base import EMPTY_KECCAK, EMPTY_TRIE_HASH
from khipu_tpu.base.crypto.keccak import keccak256, keccak512
from khipu_tpu.base.nibbles import (
    bytes_to_nibbles,
    hp_decode,
    hp_encode,
)
from khipu_tpu.base.rlp import (
    RLPError,
    decode_int,
    rlp_decode,
    rlp_encode,
    rlp_encode_int,
)


class TestKeccak:
    def test_empty(self):
        assert keccak256(b"") == EMPTY_KECCAK

    def test_abc(self):
        assert (
            keccak256(b"abc").hex()
            == "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
        )

    def test_empty_trie_root(self):
        # root of the empty MPT = keccak256(rlp(b""))
        assert keccak256(rlp_encode(b"")) == EMPTY_TRIE_HASH

    def test_multiblock_absorb_vs_hashlib_sha3(self):
        # Independent cross-validation of the permutation + multi-block
        # absorb loop: our sponge with NIST domain byte 0x06 must equal
        # hashlib's SHA3-256 (OpenSSL). Combined with the single-block
        # Keccak known-answer vectors (which pin the 0x01 domain), this
        # covers the whole multi-block path.
        import hashlib

        from khipu_tpu.base.crypto.keccak import sha3_256

        for n in (0, 1, 135, 136, 137, 272, 500, 1000, 4096):
            data = bytes((i * 7 + n) % 256 for i in range(n))
            assert sha3_256(data) == hashlib.sha3_256(data).digest(), n

    def test_keccak512_len(self):
        assert len(keccak512(b"khipu")) == 64

    def test_rate_boundary(self):
        # exactly one rate block of input → two permutations (pad block)
        for n in (135, 136, 137, 271, 272, 273):
            assert len(keccak256(b"\x5a" * n)) == 32


class TestRLP:
    @pytest.mark.parametrize(
        "value,encoded",
        [
            (b"dog", bytes([0x83]) + b"dog"),
            (b"", bytes([0x80])),
            (b"\x0f", bytes([0x0F])),
            (b"\x04\x00", bytes([0x82, 0x04, 0x00])),
            ([], bytes([0xC0])),
            ([b"cat", b"dog"], bytes([0xC8, 0x83]) + b"cat" + bytes([0x83]) + b"dog"),
        ],
    )
    def test_yellow_paper_vectors(self, value, encoded):
        assert rlp_encode(value) == encoded
        assert rlp_decode(encoded) == value

    def test_long_string(self):
        s = b"Lorem ipsum dolor sit amet, consectetur adipisicing elit"
        enc = rlp_encode(s)
        assert enc[:2] == bytes([0xB8, 0x38])
        assert rlp_decode(enc) == s

    def test_nested_list(self):
        v = [[], [[]], [[], [[]]]]
        assert rlp_decode(rlp_encode(v)) == v

    def test_long_list(self):
        v = [b"x" * 40, b"y" * 40]
        enc = rlp_encode(v)
        assert enc[0] == 0xF8
        assert rlp_decode(enc) == v

    def test_scalars(self):
        assert rlp_encode_int(0) == bytes([0x80])
        assert rlp_encode_int(15) == bytes([0x0F])
        assert rlp_encode_int(1024) == bytes([0x82, 0x04, 0x00])
        assert decode_int(b"\x04\x00") == 1024
        assert decode_int(b"") == 0

    def test_reject_noncanonical(self):
        with pytest.raises(RLPError):
            rlp_decode(bytes([0x81, 0x05]))  # single byte <0x80 must be itself
        with pytest.raises(RLPError):
            rlp_decode(bytes([0x83]) + b"ab")  # truncated
        with pytest.raises(RLPError):
            rlp_decode(rlp_encode(b"dog") + b"!")  # trailing bytes
        with pytest.raises(RLPError):
            decode_int(b"\x00\x01")  # leading zero scalar

    def test_depth_cap(self):
        # adversarial deep nesting must be a clean RLPError, not RecursionError
        payload = bytes([0xC0])
        for _ in range(200):
            n = len(payload)
            if n < 56:
                payload = bytes([0xC0 + n]) + payload
            else:
                lb = n.to_bytes((n.bit_length() + 7) // 8, "big")
                payload = bytes([0xF7 + len(lb)]) + lb + payload
        with pytest.raises(RLPError):
            rlp_decode(payload)
        v = b"x"
        for _ in range(100):
            v = [v]
        with pytest.raises(RLPError):
            rlp_encode(v)

    def test_roundtrip_large(self):
        payload = [bytes([i % 256]) * (i % 70) for i in range(200)]
        assert rlp_decode(rlp_encode(payload)) == payload


class TestHexPrefix:
    def test_bytes_to_nibbles(self):
        assert bytes_to_nibbles(b"\x12\xab") == bytes([1, 2, 0xA, 0xB])

    @pytest.mark.parametrize(
        "nibbles,is_leaf,expect",
        [
            # Yellow Paper / ethereum wiki hex-prefix examples
            (bytes([1, 2, 3, 4, 5]), False, bytes([0x11, 0x23, 0x45])),
            (bytes([0, 1, 2, 3, 4, 5]), False, bytes([0x00, 0x01, 0x23, 0x45])),
            (bytes([0, 0xF, 1, 0xC, 0xB, 8]), True, bytes([0x20, 0x0F, 0x1C, 0xB8])),
            (bytes([0xF, 1, 0xC, 0xB, 8]), True, bytes([0x3F, 0x1C, 0xB8])),
        ],
    )
    def test_hp_vectors(self, nibbles, is_leaf, expect):
        assert hp_encode(nibbles, is_leaf) == expect
        assert hp_decode(expect) == (nibbles, is_leaf)

    def test_roundtrip(self):
        for n in range(0, 10):
            nib = bytes(i % 16 for i in range(n))
            for leaf in (False, True):
                assert hp_decode(hp_encode(nib, leaf)) == (nib, leaf)


class TestNativeRLPCodec:
    """The C-extension RLP codec (native/csrc_ext/rlp_ext.c) must be
    bit-identical to the pure-Python reference, including canonical-
    form rejection and the nesting cap."""

    def test_differential_fuzz(self):
        import random

        from khipu_tpu.base import rlp as R

        rng = random.Random(99)

        def rand_item(depth=0):
            if depth > 3 or rng.random() < 0.6:
                return rng.randbytes(rng.randint(0, 90))
            return [rand_item(depth + 1) for _ in range(rng.randint(0, 6))]

        def norm(x):
            if isinstance(x, list):
                return [norm(i) for i in x]
            return bytes(x)

        for _ in range(500):
            it = rand_item()
            enc = R.rlp_encode(it)
            assert enc == R._py_rlp_encode(it)
            assert R.rlp_decode(enc) == norm(it)
            assert R._py_rlp_decode(enc) == R.rlp_decode(enc)

    def test_error_parity(self):
        import pytest as _pytest

        from khipu_tpu.base import rlp as R

        for bad in (b"", b"\x81\x05", b"\xb8\x01a", b"\xc1", b"\x80x"):
            with _pytest.raises(R.RLPError):
                R.rlp_decode(bad)
            with _pytest.raises(R.RLPError):
                R._py_rlp_decode(bad)

    def test_depth_cap(self):
        import pytest as _pytest

        from khipu_tpu.base import rlp as R

        deep = [b"h"]
        for _ in range(R.MAX_DEPTH + 5):
            deep = [deep]
        with _pytest.raises(R.RLPError):
            R.rlp_encode(deep)
        with _pytest.raises(R.RLPError):
            R._py_rlp_encode(deep)
