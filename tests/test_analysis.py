"""khipu-lint (khipu_tpu/analysis/ — docs/static_analysis.md).

Per-rule known-bad fixtures prove each rule still fires; pragma and
baseline tests prove both suppression channels; the lock-cycle fixture
proves KL004's order analysis; the self-scan tests pin the acceptance
gate — the committed tree is clean modulo a near-empty baseline and
has zero lock-order cycles.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from khipu_tpu.analysis import run_analysis
from khipu_tpu.analysis.core import Finding, load_baseline, load_project
from khipu_tpu.analysis.lockorder import LockOrderAnalysis
from khipu_tpu.analysis.report import render_json
from khipu_tpu.analysis.rules import ALL_RULES, RULES_BY_ID

REPO_ROOT = Path(__file__).resolve().parent.parent


def _scan(tmp_path, files, rules=None):
    """Write {relpath: source} under tmp_path and lint it with an
    empty baseline; returns the new findings."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    result = run_analysis([str(tmp_path)], rules=rules, baseline={})
    return result["findings"]


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------ per-rule fixtures


class TestRuleFixtures:
    def test_kl001_unledgered_crossing_fires(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "import jax\n"
            "def pull(x):\n"
            "    return jax.device_get(x)\n"
        )})
        assert _rules_of(findings) == ["KL001"]
        assert "device_get" in findings[0].message
        assert findings[0].context == "pull"

    def test_kl001_metered_forms_are_clean(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "import jax\n"
            "def timed(x):\n"
            "    with LEDGER.transfer('ops.keccak', 'd2h', 4):\n"
            "        return jax.device_get(x)\n"
            "def oneshot(x):\n"
            "    out = jax.device_get(x)\n"
            "    LEDGER.record('ops.keccak', 'd2h', 4)\n"
            "    return out\n"
        )})
        assert findings == []

    def test_kl001_misspelled_site_fires(self, tmp_path):
        """A metered crossing with a site string outside
        profiler.KNOWN_SITES still trips KL001: the bytes land in the
        totals but fork their own series and vanish from the window
        report's class breakdown."""
        findings = _scan(tmp_path, {"mod.py": (
            "import jax\n"
            "def timed(x):\n"
            "    with LEDGER.transfer('fused.colect', 'd2h', 4):\n"
            "        return jax.device_get(x)\n"
            "def oneshot(x):\n"
            "    out = jax.device_get(x)\n"
            "    LEDGER.record('mirror.admitt', 'h2d', 4)\n"
            "    return out\n"
        )})
        assert _rules_of(findings) == ["KL001"]
        msgs = sorted(f.message for f in findings)
        assert any("fused.colect" in m for m in msgs)
        assert any("mirror.admitt" in m for m in msgs)
        assert all("KNOWN_SITES" in m for m in msgs)

    def test_kl001_seal_subphase_sites_are_known(self, tmp_path):
        """The seal sub-phase sites the microscope meters through are
        registered in KNOWN_SITES — instrumented crossings tagged with
        them lint clean."""
        findings = _scan(tmp_path, {"mod.py": (
            "import jax\n"
            "def up(x):\n"
            "    with LEDGER.transfer('seal.upload', 'h2d', 4):\n"
            "        return jax.device_put(x)\n"
            "def roots(x):\n"
            "    with LEDGER.transfer('seal.rootcheck', 'd2h', 4):\n"
            "        return jax.device_get(x)\n"
            "def gather(x):\n"
            "    out = jax.device_get(x)\n"
            "    LEDGER.record('seal.alias_gather', 'h2d', 4)\n"
            "    return out\n"
        )})
        assert findings == []

    def test_kl001_misspelled_seal_subphase_fires(self, tmp_path):
        """A typo'd sub-phase site would fork its own series and fall
        out of the cost model's join — KL001 catches it lexically."""
        findings = _scan(tmp_path, {"mod.py": (
            "import jax\n"
            "def up(x):\n"
            "    with LEDGER.transfer('seal.uplaod', 'h2d', 4):\n"
            "        return jax.device_put(x)\n"
        )})
        assert _rules_of(findings) == ["KL001"]
        assert "seal.uplaod" in findings[0].message
        assert "KNOWN_SITES" in findings[0].message

    def test_kl001_dynamic_site_is_out_of_scope(self, tmp_path):
        """A non-literal site expression can't be validated lexically —
        the rule stays quiet rather than guessing."""
        findings = _scan(tmp_path, {"mod.py": (
            "import jax\n"
            "def timed(x, site):\n"
            "    with LEDGER.transfer(site, 'd2h', 4):\n"
            "        return jax.device_get(x)\n"
        )})
        assert findings == []

    def test_kl001_block_until_ready_and_from_import(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "from jax import device_put\n"
            "def up(arr, x):\n"
            "    arr.block_until_ready()\n"
            "    return device_put(x)\n"
        )})
        assert [f.rule for f in findings] == ["KL001", "KL001"]

    def test_kl002_broad_except_without_reraise_fires(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "def swallow():\n"
            "    try:\n"
            "        work()\n"
            "    except:\n"
            "        pass\n"
            "def swallow2():\n"
            "    try:\n"
            "        work()\n"
            "    except BaseException:\n"
            "        log()\n"
        )})
        assert [f.rule for f in findings] == ["KL002", "KL002"]

    def test_kl002_reraise_and_narrow_except_are_clean(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "def ok():\n"
            "    try:\n"
            "        work()\n"
            "    except BaseException:\n"
            "        cleanup()\n"
            "        raise\n"
            "def narrow():\n"
            "    try:\n"
            "        work()\n"
            "    except ValueError:\n"
            "        pass\n"
        )})
        assert findings == []

    def test_kl003_fires_only_in_protected_paths(self, tmp_path):
        src = (
            "import time, random\n"
            "def jitter():\n"
            "    return time.time() + random.random()\n"
        )
        # same source: flagged under sync/, ignored under tools/
        bad = _scan(tmp_path, {"sync/mod.py": src})
        assert [f.rule for f in bad] == ["KL003", "KL003"]
        ok = _scan(tmp_path, {"tools/mod.py": src})
        assert [f for f in ok if f.path.endswith("tools/mod.py")] == []

    def test_kl003_seeded_rng_is_clean(self, tmp_path):
        findings = _scan(tmp_path, {"sync/mod.py": (
            "import random\n"
            "RNG = random.Random(7)\n"
            "def jitter():\n"
            "    return RNG.random()\n"
        )})
        assert findings == []

    def test_kl004_lock_order_cycle_detected(self, tmp_path):
        files = {"locks.py": (
            "import threading\n"
            "A = threading.Lock()\n"
            "B = threading.Lock()\n"
            "def ab():\n"
            "    with A:\n"
            "        with B:\n"
            "            pass\n"
            "def ba():\n"
            "    with B:\n"
            "        with A:\n"
            "            pass\n"
        )}
        findings = _scan(tmp_path, files, rules=[RULES_BY_ID["KL004"]])
        assert any(
            f.rule == "KL004" and "cycle" in f.message for f in findings
        )
        # the gate surface agrees: one SCC spanning both locks
        project = load_project([str(tmp_path)])
        cycles = LockOrderAnalysis(project).cycles()
        assert len(cycles) == 1 and len(cycles[0]) == 2

    def test_kl004_blocking_call_under_lock_warns(self, tmp_path):
        findings = _scan(tmp_path, {"locks.py": (
            "import threading, time\n"
            "A = threading.Lock()\n"
            "def hold_and_sleep():\n"
            "    with A:\n"
            "        time.sleep(1)\n"
        )}, rules=[RULES_BY_ID["KL004"]])
        assert any(
            f.rule == "KL004" and "sleep" in f.message for f in findings
        )

    def test_kl004_inherited_method_resolves_through_mro(self, tmp_path):
        """ISSUE 11: ``self.m()`` where ``m`` lives on a BASE class
        still contributes its lock acquisitions to the caller's
        lockset — a subclass cannot hide a base method's nested lock
        from the order analysis."""
        files = {"locks.py": (
            "import threading\n"
            "A = threading.Lock()\n"
            "B = threading.Lock()\n"
            "class Base:\n"
            "    def inner(self):\n"
            "        with B:\n"
            "            pass\n"
            "class Sub(Base):\n"
            "    def outer(self):\n"
            "        with A:\n"
            "            self.inner()\n"
            "def ba():\n"
            "    with B:\n"
            "        with A:\n"
            "            pass\n"
        )}
        findings = _scan(tmp_path, files, rules=[RULES_BY_ID["KL004"]])
        assert any(
            f.rule == "KL004" and "cycle" in f.message for f in findings
        )
        project = load_project([str(tmp_path)])
        cycles = LockOrderAnalysis(project).cycles()
        assert len(cycles) == 1 and len(cycles[0]) == 2

    def test_kl004_callable_passed_as_argument_resolves(self, tmp_path):
        """ISSUE 11: a function REFERENCE handed to another callable
        under a held lock is a call edge — registry collectors and
        ``_call(endpoint, op)`` trampolines must not blind the
        analysis."""
        files = {"locks.py": (
            "import threading\n"
            "A = threading.Lock()\n"
            "B = threading.Lock()\n"
            "def takes_b():\n"
            "    with B:\n"
            "        pass\n"
            "def run(fn):\n"
            "    fn()\n"
            "def ab():\n"
            "    with A:\n"
            "        run(takes_b)\n"
            "def ba():\n"
            "    with B:\n"
            "        with A:\n"
            "            pass\n"
        )}
        findings = _scan(tmp_path, files, rules=[RULES_BY_ID["KL004"]])
        assert any(
            f.rule == "KL004" and "cycle" in f.message for f in findings
        )
        project = load_project([str(tmp_path)])
        cycles = LockOrderAnalysis(project).cycles()
        assert len(cycles) == 1 and len(cycles[0]) == 2

    def test_kl004_consistent_order_is_clean(self, tmp_path):
        findings = _scan(tmp_path, {"locks.py": (
            "import threading\n"
            "A = threading.Lock()\n"
            "B = threading.Lock()\n"
            "def ab():\n"
            "    with A:\n"
            "        with B:\n"
            "            pass\n"
            "def ab2():\n"
            "    with A:\n"
            "        with B:\n"
            "            pass\n"
        )}, rules=[RULES_BY_ID["KL004"]])
        assert findings == []

    LOCKSET_SRC = (
        "import threading\n"
        "class Pump:\n"
        "    def __init__(self):\n"
        "        self.lock = threading.Lock()\n"
        "        self.count = 0\n"
        "        self.depth = 0\n"
        "    def _loop(self):\n"
        "        self.count = self.count + 1\n"
        "        with self.lock:\n"
        "            self.depth = 1\n"
        "    def kick(self):\n"
        "        t = threading.Thread(target=self._loop)\n"
        "        t.start()\n"
        "        self.count = 5\n"
        "        with self.lock:\n"
        "            self.depth = 2\n"
    )

    def test_kl004_lockset_unlocked_shared_write_fires(self, tmp_path):
        """ISSUE 15: ``count`` is written by the spawned thread AND
        its spawner with no lock in either write's lockset."""
        findings = _scan(tmp_path, {"mod.py": self.LOCKSET_SRC},
                         rules=[RULES_BY_ID["KL004"]])
        hits = [f for f in findings if "no common lock" in f.message]
        assert len(hits) == 1
        assert "Pump.count" in hits[0].message
        assert hits[0].severity == "warning"
        assert hits[0].context == "Pump.count"

    def test_kl004_lockset_common_lock_is_clean(self, tmp_path):
        """``depth`` is written from the same two entry points but
        both writes hold ``self.lock`` — no finding; ``__init__``
        writes never count as sharing."""
        findings = _scan(tmp_path, {"mod.py": self.LOCKSET_SRC},
                         rules=[RULES_BY_ID["KL004"]])
        assert not any("depth" in f.message for f in findings)

    def test_kl004_lockset_single_root_is_clean(self, tmp_path):
        """One thread entry point writing an attr — even unlocked —
        is not a race by itself."""
        findings = _scan(tmp_path, {"mod.py": (
            "import threading\n"
            "class Solo:\n"
            "    def __init__(self):\n"
            "        self.n = 0\n"
            "        threading.Thread(target=self._loop).start()\n"
            "    def _loop(self):\n"
            "        self.n = 1\n"
        )}, rules=[RULES_BY_ID["KL004"]])
        assert not any("no common lock" in f.message for f in findings)

    def test_kl005_span_outside_with_fires(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "def f():\n"
            "    sp = span('work')\n"
            "def ok():\n"
            "    with span('work'):\n"
            "        pass\n"
        )})
        assert [f.rule for f in findings] == ["KL005"]
        assert findings[0].context == "f"

    def test_kl005_registry_family_in_function_fires(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "def lazy(registry):\n"
            "    return registry.counter('n')\n"
            "def labeled_child(registry):\n"
            "    return registry.counter('n', labels={'k': 'v'})\n"
        )})
        assert [f.rule for f in findings] == ["KL005"]
        assert findings[0].context == "lazy"

    def test_kl006_mutable_default_fires(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "def f(x=[]):\n"
            "    return x\n"
            "def g(*, y={}):\n"
            "    return y\n"
            "def ok(z=(), w=None):\n"
            "    return z, w\n"
        )})
        assert [f.rule for f in findings] == ["KL006", "KL006"]

    def test_kl000_parse_error_reported(self, tmp_path):
        findings = _scan(tmp_path, {"broken.py": "def f(:\n"})
        assert [f.rule for f in findings] == ["KL000"]


# ------------------------------------------------------------ suppression


class TestSuppression:
    BAD = "def f(x=[]):\n    return x\n"

    def test_pragma_on_line_suppresses(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "def f(x=[]):  # khipu-lint: ok KL006 fixture\n"
            "    return x\n"
        )})
        assert findings == []

    def test_pragma_block_above_suppresses(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "# khipu-lint: ok KL006 the reason spans a comment\n"
            "# block; the pragma may sit anywhere inside it\n"
            "def f(x=[]):\n"
            "    return x\n"
        )})
        assert findings == []

    def test_pragma_for_other_rule_does_not_suppress(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "def f(x=[]):  # khipu-lint: ok KL001 wrong rule\n"
            "    return x\n"
        )})
        assert [f.rule for f in findings] == ["KL006"]

    def test_pragma_inside_string_is_inert(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "P = '# khipu-lint: ok KL006 not a comment'\n"
            "def f(x=[]):\n"
            "    return x\n"
        )})
        assert [f.rule for f in findings] == ["KL006"]

    def test_baseline_suppresses_and_line_drift_survives(self, tmp_path):
        first = _scan(tmp_path, {"mod.py": self.BAD})
        assert len(first) == 1
        baseline = {f.fingerprint: {"rule": f.rule} for f in first}
        # shift the finding down two lines — fingerprint is line-free
        (tmp_path / "mod.py").write_text("import os\nimport sys\n"
                                         + self.BAD)
        result = run_analysis([str(tmp_path)], baseline=baseline)
        assert result["findings"] == []
        assert [f.rule for f in result["baselined"]] == ["KL006"]
        assert result["stale"] == []

    def test_stale_baseline_entries_surface(self, tmp_path):
        (tmp_path / "mod.py").write_text("def ok():\n    pass\n")
        baseline = {"KL006|gone.py|f|msg": {"rule": "KL006",
                                            "path": "gone.py"}}
        result = run_analysis([str(tmp_path)], baseline=baseline)
        assert result["findings"] == []
        assert len(result["stale"]) == 1


# ------------------------------------------------------- report + CLI


class TestReportAndCli:
    def test_json_report_is_valid_sarif_ish(self, tmp_path):
        findings = _scan(tmp_path, {"mod.py": (
            "def f(x=[]):\n    return x\n"
        )})
        doc = json.loads(render_json(findings, [], []))
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert {r.id for r in ALL_RULES} <= rule_ids
        res = run["results"][0]
        assert res["ruleId"] == "KL006"
        assert res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("mod.py")
        assert loc["region"]["startLine"] >= 1

    def test_cli_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def f(x=[]):\n    return x\n")
        good = tmp_path / "good.py"
        good.write_text("def f(x=None):\n    return x\n")

        def lint(*argv):
            return subprocess.run(
                [sys.executable, "-m", "khipu_tpu.analysis", *argv],
                cwd=REPO_ROOT, capture_output=True, text=True,
            )

        r = lint(str(good), "--no-baseline")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "clean" in r.stdout
        r = lint(str(bad), "--no-baseline")
        assert r.returncode == 1
        assert "KL006" in r.stdout
        r = lint(str(bad), "--no-baseline", "--format=json")
        assert r.returncode == 1
        assert json.loads(r.stdout)["runs"][0]["results"]

    def test_annotations_render_and_cli_annotate(self, tmp_path):
        """--annotate (review-tooling mode, the PR-10 satellite):
        findings print as ``file:line: [KL00x] msg`` lines and the
        SARIF-ish JSON document lands at the given path."""
        from khipu_tpu.analysis.report import render_annotations

        findings = _scan(tmp_path, {"mod.py": (
            "def f(x=[]):\n    return x\n"
        )})
        ann = render_annotations(findings)
        first = ann.splitlines()[0]
        assert first.endswith(findings[0].message)
        assert f":{findings[0].line}: [KL006] " in first
        assert first.startswith(findings[0].path)

        bad = tmp_path / "bad.py"
        bad.write_text("def f(x=[]):\n    return x\n")
        artifact = tmp_path / "findings.json"
        r = subprocess.run(
            [sys.executable, "-m", "khipu_tpu.analysis", str(bad),
             "--no-baseline", "--annotate", str(artifact)],
            cwd=REPO_ROOT, capture_output=True, text=True,
        )
        assert r.returncode == 1
        assert f"{bad}:1: [KL006]" in r.stdout, r.stdout
        assert str(artifact) in r.stdout  # artifact path announced
        doc = json.loads(artifact.read_text())
        assert doc["runs"][0]["results"][0]["ruleId"] == "KL006"

    def test_cli_rules_filter(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def f(x=[]):\n    return x\n")
        r = subprocess.run(
            [sys.executable, "-m", "khipu_tpu.analysis", str(bad),
             "--no-baseline", "--rules", "KL001"],
            cwd=REPO_ROOT, capture_output=True, text=True,
        )
        assert r.returncode == 0  # KL006 not selected


# --------------------------------------------------- self-scan (the gate)


class TestSelfScan:
    def test_committed_tree_is_clean_modulo_baseline(self):
        """The acceptance gate: `python -m khipu_tpu.analysis
        khipu_tpu/` exits 0 on the committed tree."""
        r = subprocess.run(
            [sys.executable, "-m", "khipu_tpu.analysis", "khipu_tpu"],
            cwd=REPO_ROOT, capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stdout + r.stderr

    def test_baseline_stays_near_empty(self):
        assert len(load_baseline()) <= 5

    def test_repo_has_no_lock_order_cycles(self):
        project = load_project([str(REPO_ROOT / "khipu_tpu")])
        assert LockOrderAnalysis(project).cycles() == []

    def test_finding_fingerprint_is_line_free(self):
        a = Finding("KL006", "error", "p.py", 10, "m", "f")
        b = Finding("KL006", "error", "p.py", 99, "m", "f")
        assert a.fingerprint == b.fingerprint
