"""Miner + log filters tests (parity targets mining/Miner.scala:40,
BlockGenerator.scala:31, jsonrpc/FilterManager.scala:86)."""

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import fixture_config
from khipu_tpu.consensus.ethash import EthashCache, check_pow
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import (
    Transaction,
    contract_address,
    sign_transaction,
)
from khipu_tpu.jsonrpc import EthService
from khipu_tpu.jsonrpc.filters import LogQuery, get_logs
from khipu_tpu.mining import Miner
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.txpool import PendingTransactionsPool

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(3)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ALLOC = {a: 10**21 for a in ADDRS}

# contract whose runtime LOG1s topic 0x..42 with 32 bytes of data
# runtime: PUSH32 <data> PUSH1 0 MSTORE PUSH32 <topic> PUSH1 32 PUSH1 0 LOG1 STOP
_TOPIC = (0x42).to_bytes(32, "big")
RUNTIME = (
    bytes([0x7F]) + b"\xab" * 32 + bytes.fromhex("600052")
    + bytes([0x7F]) + _TOPIC + bytes.fromhex("60206000a100")
)
_SS = b""
_COPY = bytes(
    [0x60, len(RUNTIME), 0x60, 12, 0x60, 0x00, 0x39,
     0x60, len(RUNTIME), 0x60, 0x00, 0xF3]
)
INIT = _COPY + RUNTIME


def fresh_chain():
    bc = Blockchain(Storages(), CFG)
    builder = ChainBuilder(bc, CFG, GenesisSpec(alloc=ALLOC))
    return bc, builder


class TestMiner:
    def test_mines_pool_txs_without_seal(self):
        bc, _ = fresh_chain()
        pool = PendingTransactionsPool()
        pool.add(sign_transaction(
            Transaction(0, 10**9, 21000, ADDRS[1], 7), KEYS[0], chain_id=1
        ))
        pool.add(sign_transaction(
            Transaction(0, 10**9, 21000, ADDRS[2], 9), KEYS[1], chain_id=1
        ))
        miner = Miner(bc, CFG, pool, coinbase=b"\xaa" * 20)
        block = miner.mine_next()
        assert block.number == 1
        assert len(block.body.transactions) == 2
        assert len(pool) == 0  # mined txs removed
        # ADDRS[1] received 7 and also sent 9 + fee in the same block
        assert bc.get_account(
            ADDRS[1], block.header.state_root
        ).balance == 10**21 + 7 - 9 - 21000 * 10**9

    def test_drops_invalid_tx_and_mines_rest(self):
        bc, _ = fresh_chain()
        pool = PendingTransactionsPool()
        pool.add(sign_transaction(
            Transaction(5, 10**9, 21000, ADDRS[1], 1), KEYS[0], chain_id=1
        ))  # wrong nonce: invalid
        pool.add(sign_transaction(
            Transaction(0, 10**9, 21000, ADDRS[0], 3), KEYS[1], chain_id=1
        ))
        miner = Miner(bc, CFG, pool, coinbase=b"\xaa" * 20)
        block = miner.mine_next()
        assert len(block.body.transactions) == 1
        assert block.body.transactions[0].sender == ADDRS[1]

    def test_sealed_mining_validates(self, monkeypatch):
        # dev-grade difficulty: drop the consensus floor so the seal
        # search finishes in CI budget (the sealing algorithm and the
        # check are identical at any difficulty)
        import khipu_tpu.domain.difficulty as diff_mod

        monkeypatch.setattr(diff_mod, "MIN_DIFFICULTY", 4)
        pool = PendingTransactionsPool()
        pool.add(sign_transaction(
            Transaction(0, 10**9, 21000, ADDRS[1], 1), KEYS[0], chain_id=1
        ))
        cache = EthashCache(0, cache_bytes=64 * 256)
        full = 64 * 1024
        bc2 = Blockchain(Storages(), CFG)
        ChainBuilder(bc2, CFG, GenesisSpec(alloc=ALLOC, difficulty=4))
        miner = Miner(
            bc2, CFG, pool, coinbase=b"\xaa" * 20,
            ethash_cache=cache, full_size=full,
        )
        block = miner.mine_next()
        pow_hash = keccak256(block.header.encode_without_nonce())
        assert check_pow(
            cache, pow_hash, block.header.mix_hash,
            int.from_bytes(block.header.nonce, "big"),
            block.header.difficulty, full,
        )
        # the sealed block is the stored head
        assert bc2.get_header_by_number(1).hash == block.hash


class TestFilters:
    @pytest.fixture()
    def chain_with_logs(self):
        bc, builder = fresh_chain()
        deploy = sign_transaction(
            Transaction(0, 10**9, 300_000, None, 0, INIT), KEYS[0],
            chain_id=1,
        )
        builder.add_block([deploy], coinbase=b"\xaa" * 20)
        caddr = contract_address(ADDRS[0], 0)
        # two blocks that emit the log + one quiet transfer block
        builder.add_block(
            [sign_transaction(
                Transaction(1, 10**9, 100_000, caddr, 0), KEYS[0], chain_id=1
            )],
            coinbase=b"\xaa" * 20,
        )
        builder.add_block(
            [sign_transaction(
                Transaction(0, 10**9, 21_000, ADDRS[1], 1), KEYS[1], chain_id=1
            )],
            coinbase=b"\xaa" * 20,
        )
        builder.add_block(
            [sign_transaction(
                Transaction(2, 10**9, 100_000, caddr, 0), KEYS[0], chain_id=1
            )],
            coinbase=b"\xaa" * 20,
        )
        return bc, builder, caddr

    def test_get_logs_by_address_and_topic(self, chain_with_logs):
        bc, _, caddr = chain_with_logs
        hits = get_logs(bc, LogQuery(0, 4, addresses=(caddr,)))
        assert [h.block_number for h in hits] == [2, 4]
        assert all(h.topics[0] == _TOPIC for h in hits)
        assert all(h.data == b"\xab" * 32 for h in hits)
        # topic filter
        assert get_logs(
            bc, LogQuery(0, 4, topics=((_TOPIC,),))
        ) == hits
        assert get_logs(
            bc, LogQuery(0, 4, topics=((b"\x00" * 32,),))
        ) == []
        # range restriction
        assert [h.block_number for h in get_logs(
            bc, LogQuery(3, 4, addresses=(caddr,))
        )] == [4]

    def test_get_logs_truncated_body_all_or_nothing(self, chain_with_logs):
        """A block whose stored body no longer covers its receipts
        (mid-reorg truncation) must contribute NO hits — not a partial
        set — while other blocks still report."""
        bc, _, caddr = chain_with_logs
        baseline = get_logs(bc, LogQuery(0, 4, addresses=(caddr,)))
        assert [h.block_number for h in baseline] == [2, 4]
        # truncate block 2's body to zero transactions
        from khipu_tpu.domain.block import BlockBody

        bc.storages.block_body_storage.put(2, BlockBody().encode())
        hits = get_logs(bc, LogQuery(0, 4, addresses=(caddr,)))
        assert [h.block_number for h in hits] == [4]
        # body missing entirely: same outcome
        bc.storages.block_body_storage.source.remove(2)
        hits = get_logs(bc, LogQuery(0, 4, addresses=(caddr,)))
        assert [h.block_number for h in hits] == [4]

    def test_eth_getLogs_rpc(self, chain_with_logs):
        bc, _, caddr = chain_with_logs
        svc = EthService(bc, CFG)
        out = svc.eth_getLogs({
            "fromBlock": "0x0", "toBlock": "latest",
            "address": "0x" + caddr.hex(),
        })
        assert len(out) == 2
        assert out[0]["blockNumber"] == "0x2"
        assert out[0]["topics"] == ["0x" + _TOPIC.hex()]

    def test_filter_polling(self, chain_with_logs):
        bc, builder, caddr = chain_with_logs
        svc = EthService(bc, CFG)
        fid = svc.eth_newFilter({
            "fromBlock": "0x0", "toBlock": hex(10**6),
            "address": "0x" + caddr.hex(),
        })
        first = svc.eth_getFilterChanges(fid)
        assert len(first) == 2  # catches up to head
        assert svc.eth_getFilterChanges(fid) == []  # no new blocks
        # new block with a log -> one new change
        builder.add_block(
            [sign_transaction(
                Transaction(3, 10**9, 100_000, caddr, 0), KEYS[0], chain_id=1
            )],
            coinbase=b"\xaa" * 20,
        )
        assert len(svc.eth_getFilterChanges(fid)) == 1
        assert svc.eth_uninstallFilter(fid)
        from khipu_tpu.jsonrpc.eth_service import RpcError

        with pytest.raises(RpcError):
            svc.eth_getFilterChanges(fid)

    def test_block_filter(self, chain_with_logs):
        bc, builder, _ = chain_with_logs
        svc = EthService(bc, CFG)
        fid = svc.eth_newBlockFilter()
        assert svc.eth_getFilterChanges(fid) == []
        blk = builder.add_block([], coinbase=b"\xaa" * 20)
        changes = svc.eth_getFilterChanges(fid)
        assert changes == ["0x" + blk.hash.hex()]


class TestMoreRpc:
    def test_pending_tx_filter_and_counts(self):
        bc, builder = fresh_chain()
        builder.add_block(
            [sign_transaction(
                Transaction(0, 10**9, 21000, ADDRS[1], 1), KEYS[0], chain_id=1
            )],
            coinbase=b"\xaa" * 20,
        )
        from khipu_tpu.txpool import PendingTransactionsPool

        pool = PendingTransactionsPool()
        svc = EthService(bc, CFG, pool)
        fid = svc.eth_newPendingTransactionFilter()
        assert svc.eth_getFilterChanges(fid) == []
        stx = sign_transaction(
            Transaction(1, 10**9, 21000, ADDRS[2], 2), KEYS[0], chain_id=1
        )
        svc.eth_sendRawTransaction("0x" + stx.encode().hex())
        changes = svc.eth_getFilterChanges(fid)
        assert changes == ["0x" + stx.hash.hex()]
        assert svc.eth_getFilterChanges(fid) == []
        assert svc.eth_getBlockTransactionCountByNumber("0x1") == "0x1"
        assert svc.eth_getUncleCountByBlockNumber("0x1") == "0x0"
        assert svc.eth_getBlockTransactionCountByNumber("0x9") is None

    def test_get_filter_logs_full_set(self):
        bc, builder = fresh_chain()
        deploy = sign_transaction(
            Transaction(0, 10**9, 300_000, None, 0, INIT), KEYS[0],
            chain_id=1,
        )
        builder.add_block([deploy], coinbase=b"\xaa" * 20)
        caddr = contract_address(ADDRS[0], 0)
        builder.add_block(
            [sign_transaction(
                Transaction(1, 10**9, 100_000, caddr, 0), KEYS[0], chain_id=1
            )],
            coinbase=b"\xaa" * 20,
        )
        svc = EthService(bc, CFG)
        fid = svc.eth_newFilter({"fromBlock": "0x0", "address": "0x" + caddr.hex()})
        svc.eth_getFilterChanges(fid)  # advance the delta cursor
        # full set stays available regardless of polling
        logs = svc.eth_getFilterLogs(fid)
        assert len(logs) == 1
        from khipu_tpu.jsonrpc.eth_service import RpcError
        import pytest as _p

        with _p.raises(RpcError):
            svc.eth_getFilterLogs("0x999")


def test_miner_full_dataset_seal(tmp_path):
    """Miner-grade sealing over the precomputed DAG: the sealed block
    validates on the light (validator) path — the real miner/validator
    split at a reduced epoch size."""
    from khipu_tpu.base.crypto.keccak import keccak256
    from khipu_tpu.consensus.ethash import EthashCache, check_pow
    from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
    from khipu_tpu.mining import Miner
    from khipu_tpu.storage.storages import Storages
    from khipu_tpu.txpool import PendingTransactionsPool

    from khipu_tpu.config import fixture_config

    cfg = fixture_config(chain_id=1)
    bc = Blockchain(Storages(), cfg)
    bc.load_genesis(GenesisSpec(alloc={}))
    cache = EthashCache(0, cache_bytes=1024)
    full = 64 * 128
    miner = Miner(
        bc, cfg, PendingTransactionsPool(), b"\xaa" * 20,
        ethash_cache=cache, full_size=full,
        use_dataset=True, dag_dir=str(tmp_path),
    )
    block = miner.mine_next()
    header = block.header
    assert check_pow(
        cache,
        keccak256(header.encode_without_nonce()),
        header.mix_hash,
        int.from_bytes(header.nonce, "big"),
        header.difficulty,
        full_size=full,
    )
