"""Ledger tests: world merge algebra, tx execution semantics, and the
block-replay harness end-to-end (parity targets ledger/*.scala;
SURVEY.md §4 plan items 4-5).

External (non-self-referential) oracles used: exact balance accounting
for transfers/fees/rewards, 21000 intrinsic gas, EIP-155 senders, and
parallel == sequential root equality on conflict-heavy chains.
"""

import dataclasses

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import (
    Transaction,
    contract_address,
    sign_transaction,
)
from khipu_tpu.ledger.bloom import bloom_contains, bloom_of_logs
from khipu_tpu.ledger.world import BlockWorldState
from khipu_tpu.domain.receipt import TxLogEntry
from khipu_tpu.storage.datasource import MemoryNodeDataSource
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.replay import ReplayDriver
from khipu_tpu.trie.mpt import MerklePatriciaTrie

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(6)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
MINER = b"\xaa" * 20
GWEI = 10**9
ETH = 10**18


def fresh_world():
    return BlockWorldState(
        MerklePatriciaTrie(MemoryNodeDataSource()),
        MemoryNodeDataSource(),
        MemoryNodeDataSource(),
    )


def new_chain(alloc=None, config=CFG):
    bc = Blockchain(Storages(), config)
    spec = GenesisSpec(alloc=alloc or {a: 1000 * ETH for a in ADDRS})
    return ChainBuilder(bc, config, spec), bc


def tx(i, nonce, to, value, gas=21000, payload=b"", price=GWEI):
    return sign_transaction(
        Transaction(nonce, price, gas, to, value, payload),
        KEYS[i],
        chain_id=1,
    )


class TestMergeAlgebra:
    def test_commutative_credits_merge(self):
        """Two tx worlds crediting the SAME address merge without
        conflict (the AccountDelta design, BlockWorldState.scala:59)."""
        base = fresh_world()
        w1 = fresh_world()
        w1.add_balance(ADDRS[0], 5)
        w2 = fresh_world()
        w2.add_balance(ADDRS[0], 7)
        assert base.merge(w1) is None
        assert base.merge(w2) is None
        assert base.get_balance(ADDRS[0]) == 12

    def test_read_write_conflict_detected(self):
        base = fresh_world()
        w1 = fresh_world()
        w1.add_balance(ADDRS[0], 5)
        w2 = fresh_world()
        w2.get_balance(ADDRS[0])  # reads what w1 wrote
        w2.add_balance(ADDRS[1], 1)
        assert base.merge(w1) is None
        conflict = base.merge(w2)
        assert conflict is not None and ADDRS[0] in conflict

    def test_storage_cell_conflict(self):
        base = fresh_world()
        w1 = fresh_world()
        w1.save_storage(ADDRS[0], 1, 42)
        w2 = fresh_world()
        w2.get_storage(ADDRS[0], 1)
        assert base.merge(w1) is None
        assert base.merge(w2) is not None

    def test_disjoint_storage_cells_merge(self):
        base = fresh_world()
        w1 = fresh_world()
        w1.save_storage(ADDRS[0], 1, 42)
        w2 = fresh_world()
        w2.get_storage(ADDRS[0], 2)  # different cell
        w2.save_storage(ADDRS[0], 2, 7)
        assert base.merge(w1) is None
        assert base.merge(w2) is None
        assert base.get_storage(ADDRS[0], 1) == 42
        assert base.get_storage(ADDRS[0], 2) == 7

    def test_reverted_frame_reads_survive(self):
        """copy() shares reads — a rolled-back frame's observations
        still count for race detection (runVM:728-733 semantics)."""
        w = fresh_world()
        frame = w.copy()
        frame.get_balance(ADDRS[3])
        from khipu_tpu.ledger.world import ON_ACCOUNT

        assert ADDRS[3] in w.reads[ON_ACCOUNT]


class TestTransferBlock:
    def test_balance_accounting_exact(self):
        builder, bc = new_chain()
        b1 = builder.add_block(
            [tx(0, 0, ADDRS[1], 5 * ETH)], coinbase=MINER
        )
        assert b1.header.gas_used == 21000
        root = b1.header.state_root
        sender = bc.get_account(ADDRS[0], root)
        receiver = bc.get_account(ADDRS[1], root)
        miner = bc.get_account(MINER, root)
        assert sender.balance == 1000 * ETH - 5 * ETH - 21000 * GWEI
        assert sender.nonce == 1
        assert receiver.balance == 1005 * ETH
        # miner: fee + 2 ETH Constantinople reward
        assert miner.balance == 21000 * GWEI + 2 * ETH

    def test_insufficient_balance_rejects_block(self):
        from khipu_tpu.ledger.ledger import TxValidationError

        builder, bc = new_chain(alloc={ADDRS[0]: 10**15})
        with pytest.raises(TxValidationError):
            builder.add_block([tx(0, 0, ADDRS[1], 10**18)])

    def test_wrong_nonce_rejects(self):
        from khipu_tpu.ledger.ledger import TxValidationError

        builder, bc = new_chain()
        with pytest.raises(TxValidationError):
            builder.add_block([tx(0, 3, ADDRS[1], 1)])


# A storage contract: init stores 0x2a at slot 0 and returns runtime
# code that serves SLOAD(0).
RUNTIME = bytes.fromhex("60005460005260206000f3")
_INIT = bytes.fromhex("602a600055")
_COPY = bytes(
    [0x60, len(RUNTIME), 0x60, len(_INIT) + 12, 0x60, 0x00, 0x39,
     0x60, len(RUNTIME), 0x60, 0x00, 0xF3]
)
INIT_CODE = _INIT + _COPY + RUNTIME


class TestContracts:
    def test_deploy_and_call(self):
        builder, bc = new_chain()
        deploy = tx(0, 0, None, 0, gas=500_000, payload=INIT_CODE)
        b1 = builder.add_block([deploy], coinbase=MINER)
        caddr = contract_address(ADDRS[0], 0)
        world = bc.get_world_state(b1.header.state_root)
        assert world.get_code(caddr) == RUNTIME
        assert world.get_storage(caddr, 0) == 42
        acc = bc.get_account(caddr, b1.header.state_root)
        assert acc.nonce == 1  # EIP-161 contract start nonce

        call = tx(0, 1, caddr, 0, gas=100_000)
        b2 = builder.add_block([call], coinbase=MINER)
        assert b2.header.gas_used > 21000  # SLOAD etc. on top

    def test_selfdestruct_refund_and_deletion(self):
        builder, bc = new_chain()
        # init code that immediately SELFDESTRUCTs to ADDRS[2]
        sd = bytes.fromhex("73") + ADDRS[2] + bytes.fromhex("ff")
        deploy = tx(0, 0, None, 3 * ETH, gas=200_000, payload=sd)
        b1 = builder.add_block([deploy], coinbase=MINER)
        caddr = contract_address(ADDRS[0], 0)
        assert bc.get_account(caddr, b1.header.state_root) is None
        ben = bc.get_account(ADDRS[2], b1.header.state_root)
        assert ben.balance == 1000 * ETH + 3 * ETH  # endowment forwarded

    def test_out_of_gas_tx_keeps_fee_and_nonce(self):
        builder, bc = new_chain()
        # intrinsic passes but execution OOGs (SSTORE needs 20k)
        deploy = tx(0, 0, None, 0, gas=55_000, payload=INIT_CODE)
        b1 = builder.add_block([deploy], coinbase=MINER)
        assert b1.header.gas_used == 55_000  # all gas consumed
        sender = bc.get_account(ADDRS[0], b1.header.state_root)
        assert sender.nonce == 1
        assert sender.balance == 1000 * ETH - 55_000 * GWEI
        assert bc.get_account(
            contract_address(ADDRS[0], 0), b1.header.state_root
        ) is None


class TestEIP161:
    def test_touched_empty_account_deleted(self):
        """Zero-value call to an empty account deletes it post-161."""
        builder, bc = new_chain(
            alloc={ADDRS[0]: 1000 * ETH, ADDRS[5]: 0}
        )
        g = builder.genesis
        # the zero-balance alloc account exists at genesis
        assert bc.get_account(ADDRS[5], g.header.state_root) is not None
        b1 = builder.add_block(
            [tx(0, 0, ADDRS[5], 0, gas=30_000)], coinbase=MINER
        )
        assert bc.get_account(ADDRS[5], b1.header.state_root) is None


class TestParallelExecution:
    def _chain_blocks(self, config):
        builder, bc = new_chain(config=config)
        # block 1: disjoint transfers (fully parallel) + one contract
        b1 = builder.add_block(
            [
                tx(0, 0, ADDRS[3], ETH),
                tx(1, 0, ADDRS[4], ETH),
                tx(2, 0, ADDRS[5], ETH),
            ],
            coinbase=MINER,
        )
        # block 2: conflict-heavy ring (each recipient is next sender)
        b2 = builder.add_block(
            [
                tx(0, 1, ADDRS[1], 7 * ETH),
                tx(1, 1, ADDRS[2], 5 * ETH),
                tx(2, 1, ADDRS[0], 3 * ETH),
            ],
            coinbase=MINER,
        )
        # block 3: contract deploy + unrelated transfer
        b3 = builder.add_block(
            [
                tx(0, 2, None, 0, gas=500_000, payload=INIT_CODE),
                tx(3, 0, ADDRS[4], ETH),
            ],
            coinbase=MINER,
        )
        return [b1, b2, b3]

    def test_parallel_equals_sequential(self):
        seq_cfg = dataclasses.replace(
            CFG, sync=SyncConfig(parallel_tx=False)
        )
        par_cfg = dataclasses.replace(
            CFG, sync=SyncConfig(parallel_tx=True)
        )
        blocks = self._chain_blocks(seq_cfg)
        for config in (seq_cfg, par_cfg):
            bc = Blockchain(Storages(), config)
            bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
            stats = ReplayDriver(bc, config).replay(blocks)
            assert (
                bc.get_header_by_number(3).hash == blocks[-1].hash
            ), f"divergence under parallel={config.sync.parallel_tx}"
            if config.sync.parallel_tx:
                # the disjoint-transfer block must actually merge
                assert stats.parallel_txs >= 3
                assert stats.conflicts >= 2  # the ring block conflicts

    def test_parallel_rate_reported(self):
        par_cfg = dataclasses.replace(CFG, sync=SyncConfig(parallel_tx=True))
        blocks = self._chain_blocks(par_cfg)
        bc = Blockchain(Storages(), par_cfg)
        bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
        lines = []
        ReplayDriver(bc, par_cfg, log=lines.append).replay(blocks)
        assert len(lines) == 3
        assert all("parallel" in line and "tx/s" in line for line in lines)


class TestBloom:
    def test_bloom_membership(self):
        log = TxLogEntry(b"\x11" * 20, (b"\x22" * 32,), b"")
        bloom = bloom_of_logs([log])
        assert bloom_contains(bloom, b"\x11" * 20)
        assert bloom_contains(bloom, b"\x22" * 32)
        assert not bloom_contains(bloom, b"\x33" * 32)
        assert sum(bin(b).count("1") for b in bloom) <= 6


class TestReplayRejectsTampering:
    def test_bad_state_root_rejected(self):
        from khipu_tpu.ledger.ledger import ValidationAfterExecError
        import dataclasses as dc

        builder, bc = new_chain()
        b1 = builder.add_block([tx(0, 0, ADDRS[1], ETH)], coinbase=MINER)
        bad_header = dc.replace(b1.header, state_root=b"\x13" * 32)
        from khipu_tpu.domain.block import Block

        bad = Block(bad_header, b1.body)
        bc2 = Blockchain(Storages(), CFG)
        bc2.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
        driver = ReplayDriver(bc2, CFG, validate_headers=False)
        with pytest.raises(ValidationAfterExecError):
            driver.replay([bad])


class TestReviewRegressions:
    """Regressions for the round-3 review findings: parallel-vs-
    sequential consensus splits that the merge algebra must prevent."""

    def test_zero_delta_does_not_create_account(self):
        w = fresh_world()
        empty_root = w.root_hash
        w.add_balance(b"\x77" * 20, 0)
        assert w.root_hash == empty_root

    def test_eip161_sweep_conflicts_with_parallel_credit(self):
        """tx0 credits empty account A; tx1 zero-transfers to A. The
        sweep's emptiness read must force a conflict so A's credit is
        not erased — sequential and parallel roots must agree."""
        import dataclasses as dc

        alloc = {ADDRS[0]: 1000 * ETH, ADDRS[1]: 1000 * ETH, ADDRS[5]: 0}
        seq_cfg = dc.replace(CFG, sync=SyncConfig(parallel_tx=False))
        par_cfg = dc.replace(CFG, sync=SyncConfig(parallel_tx=True))
        builder, _ = new_chain(alloc=alloc, config=seq_cfg)
        b1 = builder.add_block(
            [tx(0, 0, ADDRS[5], 10), tx(1, 0, ADDRS[5], 0, gas=30_000)],
            coinbase=MINER,
        )
        bc2 = Blockchain(Storages(), par_cfg)
        bc2.load_genesis(GenesisSpec(alloc=alloc))
        ReplayDriver(bc2, par_cfg).replay([b1])  # raises on divergence
        assert bc2.get_account(ADDRS[5], b1.header.state_root).balance == 10

    def test_parallel_enforces_block_gas_limit(self):
        """Two independent txs whose gas limits exceed the block limit
        together must be rejected in parallel mode too (YP eq. 58)."""
        import dataclasses as dc
        from khipu_tpu.domain.block import Block, BlockBody
        from khipu_tpu.domain.block_header import (
            EMPTY_OMMERS_HASH,
            BlockHeader,
        )
        from khipu_tpu.ledger.ledger import (
            TxValidationError,
            execute_block,
        )
        from khipu_tpu.validators.roots import transactions_root

        par_cfg = dc.replace(CFG, sync=SyncConfig(parallel_tx=True))
        bc = Blockchain(Storages(), par_cfg)
        genesis = bc.load_genesis(
            GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS})
        )
        txs = (tx(0, 0, ADDRS[3], 1, gas=40_000), tx(1, 0, ADDRS[4], 1, gas=40_000))
        header = BlockHeader(
            parent_hash=genesis.hash,
            ommers_hash=EMPTY_OMMERS_HASH,
            beneficiary=MINER,
            state_root=b"\x00" * 32,
            transactions_root=transactions_root(txs),
            receipts_root=b"\x00" * 32,
            logs_bloom=b"\x00" * 256,
            difficulty=1,
            number=1,
            gas_limit=60_000,  # < 40k + 40k
            gas_used=0,
            unix_timestamp=13,
        )
        with pytest.raises(TxValidationError):
            execute_block(
                Block(header, BlockBody(txs)),
                genesis.header.state_root,
                bc.get_world_state,
                par_cfg,
                validate=False,
            )


class TestOmmers:
    def test_ommer_rewards_through_execution(self):
        """A block including an ommer pays the ommer's beneficiary the
        distance-scaled reward and the miner the +1/32 bonus
        (BlockRewardCalculator.scala:11), replay-verified."""
        import dataclasses as dc

        builder, bc = new_chain()
        b1 = builder.add_block([], coinbase=MINER)
        # a plausible competing child of block 1's parent
        ommer = dc.replace(
            b1.header, beneficiary=ADDRS[5], extra_data=b"uncle"
        )
        b2 = builder.add_block(
            [tx(0, 0, ADDRS[1], 1)], coinbase=MINER, ommers=(ommer,)
        )
        root = b2.header.state_root
        base = 2 * ETH  # Constantinople reward (all forks active)
        # ommer at height 1 included at height 2: (8 + 1 - 2)/8 * base
        assert bc.get_account(ADDRS[5], root).balance == (
            1000 * ETH + base * 7 // 8  # genesis alloc + ommer reward
        )
        miner_acc = bc.get_account(MINER, root)
        # two blocks of base reward + 1/32 ommer bonus + the tx fee
        assert miner_acc.balance == (
            2 * base + base // 32 + 21000 * GWEI
        )
        # and the whole thing replays bit-exact
        bc2 = Blockchain(Storages(), CFG)
        bc2.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
        ReplayDriver(bc2, CFG).replay([b1, b2])
        assert bc2.get_header_by_number(2).hash == b2.hash

    def test_invalid_ommers_rejected(self):
        """OmmersValidator: ancestors, depth, and duplicates rejected
        (OmmersValidator.scala rules)."""
        import dataclasses as dc

        import pytest as _pytest

        from khipu_tpu.validators.validators import (
            OmmersValidator,
            ValidationError,
        )
        from khipu_tpu.domain.block import Block, BlockBody

        builder, bc = new_chain()
        b1 = builder.add_block([], coinbase=MINER)
        b2 = builder.add_block([], coinbase=MINER)

        def block_with(ommers):
            hdr = dc.replace(
                b2.header, number=3, parent_hash=b2.hash
            )
            return Block(hdr, BlockBody((), tuple(ommers)))

        # an actual ancestor as ommer
        with _pytest.raises(ValidationError, match="ancestor"):
            OmmersValidator.validate(bc, block_with([b1.header]))
        # duplicate ommers
        u = dc.replace(b1.header, extra_data=b"u")
        with _pytest.raises(ValidationError, match="duplicate"):
            OmmersValidator.validate(bc, block_with([u, u]))
        # too many
        us = [dc.replace(b1.header, extra_data=bytes([i])) for i in range(3)]
        with _pytest.raises(ValidationError, match="> 2"):
            OmmersValidator.validate(bc, block_with(us))
        # parent not an ancestor
        orphan = dc.replace(b1.header, parent_hash=b"\x77" * 32)
        with _pytest.raises(ValidationError, match="ancestor"):
            OmmersValidator.validate(bc, block_with([orphan]))
        # a legitimate uncle passes
        OmmersValidator.validate(bc, block_with([u]))
