"""Deferred (level-synchronous batched) trie commit tests: bit-exact
equality with the eager host MPT, and the device/mesh integrations
(SURVEY §2.8(c); round-3 brief items 1 and 6)."""

import random

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.storage.datasource import MemoryNodeDataSource
from khipu_tpu.trie.bulk import bulk_build, host_hasher
from khipu_tpu.trie.deferred import batch_commit
from khipu_tpu.trie.mpt import MerklePatriciaTrie


def eager_apply(trie, upserts, removes):
    for k in removes:
        trie = trie.remove(k)
    for k, v in upserts:
        trie = trie.put(k, v)
    return trie


class TestBatchCommit:
    def test_fresh_build_matches_eager(self):
        random.seed(1)
        pairs = [
            (keccak256(b"k%d" % i), b"value-%d" % i * (i % 7 + 1))
            for i in range(500)
        ]
        src = MemoryNodeDataSource()
        eager = eager_apply(MerklePatriciaTrie(src), pairs, [])
        deferred = batch_commit(MerklePatriciaTrie(src), pairs)
        assert deferred.root_hash == eager.root_hash
        # the change sets agree too (same node hashes)
        _, up_e = eager.changes()
        _, up_d = deferred.changes()
        assert up_e == up_d

    def test_incremental_update_matches_eager(self):
        """Block-commit shape: small dirty set against a large persisted
        trie, including removals and overwrites."""
        random.seed(2)
        base_pairs = [
            (keccak256(b"base%d" % i), b"acct-%d" % i) for i in range(2000)
        ]
        src = MemoryNodeDataSource()
        base = eager_apply(MerklePatriciaTrie(src), base_pairs, [])
        base = base.persist()

        for round_i in range(5):
            ups = [
                (keccak256(b"base%d" % random.randrange(2500)),
                 b"new-%d-%d" % (round_i, j))
                for j in range(50)
            ]
            rms = [
                keccak256(b"base%d" % random.randrange(2000))
                for _ in range(10)
            ]
            eager = eager_apply(base, ups, rms)
            deferred = batch_commit(base, ups, rms)
            assert deferred.root_hash == eager.root_hash, f"round {round_i}"
            # reads through the deferred trie resolve real hashes
            # (duplicate upsert keys: last write wins, like the eager fold)
            expected = dict(ups)
            for k, v in expected.items():
                if k not in rms:
                    assert deferred.get(k) == v
            base = deferred.persist()

    def test_persisted_deferred_trie_reopens(self):
        src = MemoryNodeDataSource()
        pairs = [(keccak256(b"p%d" % i), b"v%d" % i) for i in range(100)]
        t = batch_commit(MerklePatriciaTrie(src), pairs).persist()
        again = MerklePatriciaTrie(src, root_hash=t.root_hash)
        for k, v in pairs:
            assert again.get(k) == v

    def test_empty_batch_is_identity(self):
        src = MemoryNodeDataSource()
        base = eager_apply(
            MerklePatriciaTrie(src),
            [(keccak256(b"x"), b"y")], [],
        )
        out = batch_commit(base, [], [])
        assert out.root_hash == base.root_hash

    def test_caller_trie_untouched(self):
        src = MemoryNodeDataSource()
        base = eager_apply(MerklePatriciaTrie(src), [(keccak256(b"a"), b"1")], [])
        logs_before = {h: list(r) for h, r in base._logs.items()}
        batch_commit(base, [(keccak256(b"b"), b"2")])
        assert {h: list(r) for h, r in base._logs.items()} == logs_before


class TestWorldDeviceCommit:
    def test_replay_with_device_commit_identical_roots(self):
        """Full replay with every trie commit through the batched
        hasher: persisted roots must equal the eager-built headers."""
        from khipu_tpu.base.crypto.secp256k1 import (
            privkey_to_pubkey,
            pubkey_to_address,
        )
        from khipu_tpu.config import fixture_config
        from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
        from khipu_tpu.domain.transaction import (
            Transaction,
            sign_transaction,
        )
        from khipu_tpu.storage.storages import Storages
        from khipu_tpu.sync.chain_builder import ChainBuilder
        from khipu_tpu.sync.replay import ReplayDriver

        cfg = fixture_config(chain_id=1)
        keys = [(i + 1).to_bytes(32, "big") for i in range(3)]
        addrs = [pubkey_to_address(privkey_to_pubkey(k)) for k in keys]
        alloc = {a: 10**21 for a in addrs}
        builder = ChainBuilder(
            Blockchain(Storages(), cfg), cfg, GenesisSpec(alloc=alloc)
        )
        # include a contract so storage tries hit the deferred path too
        init = bytes.fromhex("602a600055600a600155")  # two SSTOREs
        blocks = [
            builder.add_block(
                [sign_transaction(Transaction(0, 10**9, 200_000, None, 0, init), keys[0], chain_id=1)],
                coinbase=b"\xaa" * 20,
            ),
            builder.add_block(
                [sign_transaction(Transaction(1, 10**9, 21_000, addrs[1], 5), keys[0], chain_id=1),
                 sign_transaction(Transaction(0, 10**9, 21_000, addrs[2], 7), keys[1], chain_id=1)],
                coinbase=b"\xaa" * 20,
            ),
        ]
        bc2 = Blockchain(Storages(), cfg)
        bc2.load_genesis(GenesisSpec(alloc=alloc))
        # device_commit=True -> ops.keccak batch path (jnp on CPU mesh,
        # Pallas on TPU); save_block raises if any root diverges
        ReplayDriver(bc2, cfg, device_commit=True).replay(blocks)
        assert bc2.get_header_by_number(2).hash == blocks[-1].hash


class TestShardedBulkBuild:
    def test_sharded_bulk_root_matches_host_10k(self):
        """Round-3 brief item 6 'Done =': multi-device CPU test, sharded
        bulk root == host-oracle root on a 10k-account trie."""
        import jax

        from khipu_tpu.parallel import device_mesh
        from khipu_tpu.parallel.keccak_sharded import sharded_hasher

        mesh = device_mesh(min(8, len(jax.devices())))
        pairs = [
            (keccak256(b"acct%d" % i), b"\x01" * 8 + b"%d" % i)
            for i in range(10_000)
        ]
        host_root, host_nodes = bulk_build(pairs, hasher=host_hasher)
        sh_root, sh_nodes = bulk_build(pairs, hasher=sharded_hasher(mesh))
        assert sh_root == host_root
        assert sh_nodes == host_nodes

    def test_sharded_batch_commit(self):
        """Incremental deferred commit with the mesh hasher."""
        import jax

        from khipu_tpu.parallel import device_mesh
        from khipu_tpu.parallel.keccak_sharded import sharded_hasher

        mesh = device_mesh(min(8, len(jax.devices())))
        src = MemoryNodeDataSource()
        base_pairs = [(keccak256(b"b%d" % i), b"v%d" % i) for i in range(300)]
        base = eager_apply(MerklePatriciaTrie(src), base_pairs, []).persist()
        ups = [(keccak256(b"b%d" % i), b"upd%d" % i) for i in range(0, 600, 3)]
        eager = eager_apply(base, ups, [])
        sharded = batch_commit(base, ups, hasher=sharded_hasher(mesh))
        assert sharded.root_hash == eager.root_hash


class TestFusedFinalize:
    """One-dispatch fixpoint finalize (trie/fused.py) vs the per-level
    loop — identical resolutions, roots, and persisted stores."""

    def _random_session(self, seed, n_base, n_up, n_rm):
        rng = random.Random(seed)
        src = MemoryNodeDataSource()
        base = MerklePatriciaTrie(src)
        keys = [keccak256(rng.randbytes(8)) for _ in range(n_base)]
        for k in keys:
            base = base.put(k, rng.randbytes(rng.randrange(1, 80)))
        base = base.persist()
        ups = [
            (keccak256(rng.randbytes(8)), rng.randbytes(rng.randrange(1, 80)))
            for _ in range(n_up)
        ] + [(rng.choice(keys), b"overwritten") for _ in range(5)]
        rms = rng.sample(keys, min(n_rm, len(keys)))
        return base, ups, rms

    # one seed: each distinct window shape costs a fresh XLA compile of
    # the fixpoint program (~30s on CPU); the windowed-replay test below
    # covers a second, independent shape
    @pytest.mark.parametrize("seed", [1])
    def test_fused_equals_level_loop(self, seed):
        from khipu_tpu.trie.deferred import DeferredMPT, finalize

        base, ups, rms = self._random_session(seed, 300, 200, 40)

        def session():
            d = DeferredMPT(
                base.source,
                _root_ref=base._root_ref,
                _logs={h: [c, e] for h, (c, e) in base._logs.items()},
                _staged=dict(base._staged),
            )
            for k in rms:
                d = d.remove(k)
            for k, v in ups:
                d = d.put(k, v)
            return d

        loop_trie, loop_map = finalize(
            session(), host_hasher, return_mapping=True
        )
        fused_trie, fused_map = finalize(
            session(), host_hasher, return_mapping=True, fused=True
        )
        assert fused_map and fused_map == loop_map
        assert fused_trie.root_hash == loop_trie.root_hash
        _, loop_up = loop_trie.changes()
        _, fused_up = fused_trie.changes()
        assert fused_up == loop_up
        # content addressing holds on every fused node
        for h, enc in fused_up.items():
            assert keccak256(enc) == h

    def test_fused_windowed_replay_equals_host(self):
        """End to end: windowed replay with the fused committer produces
        the same chain as the eager per-block host path."""
        import dataclasses

        from khipu_tpu.base.crypto.secp256k1 import (
            privkey_to_pubkey,
            pubkey_to_address,
        )
        from khipu_tpu.config import SyncConfig, fixture_config
        from khipu_tpu.domain.block import Block
        from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
        from khipu_tpu.domain.transaction import (
            Transaction,
            sign_transaction,
        )
        from khipu_tpu.storage.storages import Storages
        from khipu_tpu.sync.chain_builder import ChainBuilder
        from khipu_tpu.sync.replay import ReplayDriver

        cfg = fixture_config(chain_id=1)
        key = (9).to_bytes(32, "big")
        sender = pubkey_to_address(privkey_to_pubkey(key))
        alloc = {sender: 10**21}
        builder = ChainBuilder(
            Blockchain(Storages(), cfg), cfg, GenesisSpec(alloc=alloc)
        )
        blocks = []
        for n in range(9):
            txs = [
                sign_transaction(
                    Transaction(
                        n * 2 + j, 10**9, 21_000,
                        bytes.fromhex("%040x" % (0xF00D + 7 * n + j)), 5,
                    ),
                    key, chain_id=1,
                )
                for j in range(2)
            ]
            blocks.append(builder.add_block(txs, coinbase=b"\xaa" * 20))
        blocks = [Block.decode(b.encode()) for b in blocks]

        cfg2 = dataclasses.replace(
            cfg, sync=SyncConfig(parallel_tx=False, commit_window_blocks=4)
        )
        bc = Blockchain(Storages(), cfg2)
        bc.load_genesis(GenesisSpec(alloc=alloc))
        driver = ReplayDriver(bc, cfg2, device_commit=True)
        driver.hasher = host_hasher  # device kernel interpreted on CPU is
        # slow; `fused` is forced below and runs the one-dispatch path
        stats = driver.replay(blocks)
        assert stats.blocks == 9
        assert bc.get_header_by_number(9).hash == blocks[-1].hash


def test_seal_scan_matches_resolution_inputs():
    """WindowCommitter.seal derives its placeholder DAG with a raw
    byte scan (no rlp decode); deferred.resolution_inputs derives it
    from decoded structures. The two scanners must agree on the same
    session — this pins them against silent divergence (they share the
    placeholder format and the embedded-ref rules)."""
    from khipu_tpu.domain.account import Account, address_key
    from khipu_tpu.ledger.window import WindowCommitter
    from khipu_tpu.storage.storages import Storages
    from khipu_tpu.trie.deferred import resolution_inputs
    from khipu_tpu.trie.mpt import EMPTY_TRIE_HASH

    committer = WindowCommitter(Storages(), EMPTY_TRIE_HASH)
    trie = committer.account_trie
    for i in range(40):
        acc = Account(nonce=i, balance=10**18 + i)
        trie = trie.put(address_key(i.to_bytes(20, "big")), acc.encode())
    committer.account_trie = trie
    want_resolve, want_deps, _ = resolution_inputs(trie)

    job = committer.seal()
    committer.pack_and_dispatch(job)  # seal() defers the pack scan
    assert set(job.to_resolve) == set(want_resolve)
    # seal pre-substitutes resolved placeholders; with none resolved
    # yet the encodings must be byte-identical too
    assert job.to_resolve == want_resolve
