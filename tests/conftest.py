"""Test harness: force JAX onto a virtual 8-device CPU mesh.

Stands in for real multi-chip TPU hardware the same way the reference's
(unused) akka-multi-node-testkit would have stood in for a cluster
(SURVEY.md §4). Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The axon PJRT plugin (sitecustomize) force-updates jax_platforms to
# "axon,cpu" at interpreter start, which overrides the env var — pin the
# config back to CPU before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
