"""Live regular sync over real RLPx loopback sockets.

The verdict-6 scenario: a fresh node regular-syncs a 50-block chain from
a serving peer END TO END — RLPx auth, Hello/Status, batched header +
body fetch, full validated import — including one reorg (the serving
node switches to a higher-TD branch mid-sync and the syncer rolls back
to the common ancestor), and one missing-node heal through GetNodeData.

Parity: RegularSyncService.scala:103-269 (fetch loop), :336-345 (TD
reorg), :448-479 (best peer); HostService.scala (the serving side).
"""

import dataclasses
import threading
import time

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.network.host_service import HostService
from khipu_tpu.network.messages import Status
from khipu_tpu.network.peer import PeerManager
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.regular_sync import RegularSyncService
from khipu_tpu.sync.replay import ReplayDriver

PRIV_A = (0xA11CE).to_bytes(32, "big")
PRIV_B = (0xB0B).to_bytes(32, "big")
SENDER_KEY = (7).to_bytes(32, "big")
SENDER = pubkey_to_address(privkey_to_pubkey(SENDER_KEY))
ALLOC = {SENDER: 10**24}

CFG = dataclasses.replace(
    fixture_config(chain_id=1),
    sync=SyncConfig(
        parallel_tx=False, tx_workers=2, commit_window_blocks=1,
        block_resolving_depth=20,
    ),
)


def build_chain(n_blocks, diverge_at=None, fork_coinbase=b"\xbb" * 20):
    """Deterministic fixture chain; identical prefixes across calls.
    From ``diverge_at`` on, blocks use a different coinbase (a distinct
    but equally valid branch)."""
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG, GenesisSpec(alloc=ALLOC)
    )
    blocks = []
    nonce = 0
    for n in range(1, n_blocks + 1):
        coinbase = (
            fork_coinbase
            if diverge_at is not None and n >= diverge_at
            else b"\xaa" * 20
        )
        txs = [
            sign_transaction(
                Transaction(
                    nonce, 10**9, 21_000,
                    bytes.fromhex("%040x" % (0xD00D + n)), 1,
                ),
                SENDER_KEY, chain_id=1,
            )
        ]
        nonce += 1
        blocks.append(builder.add_block(txs, coinbase=coinbase))
    return blocks


def make_serving_node(blocks):
    """A blockchain with ``blocks`` imported, ready to serve."""
    bc = Blockchain(Storages(), CFG)
    bc.load_genesis(GenesisSpec(alloc=ALLOC))
    ReplayDriver(bc, CFG).replay(blocks)
    return bc


class _NodeBox:
    """Mutable holder so the server can switch chains mid-test."""

    def __init__(self, bc):
        self.bc = bc


def status_factory(box: _NodeBox):
    def make():
        bc = box.bc
        best = bc.best_block_number
        return Status(
            protocol_version=63,
            network_id=1,
            total_difficulty=bc.get_total_difficulty(best) or 0,
            best_hash=bc.get_hash_by_number(best),
            genesis_hash=bc.get_hash_by_number(0),
        )
    return make


class _SwitchingHost(HostService):
    """HostService over a switchable chain box."""

    def __init__(self, box: _NodeBox):
        self.box = box

    @property
    def blockchain(self):
        return self.box.bc

    @blockchain.setter
    def blockchain(self, v):  # HostService.__init__ assigns; ignore
        pass


@pytest.fixture
def loopback():
    managers = []

    def connect(server_box, client_box):
        server = PeerManager(
            PRIV_A, "khipu-tpu/server", status_factory(server_box)
        )
        _SwitchingHost(server_box).install(server)
        port = server.listen()
        client = PeerManager(
            PRIV_B, "khipu-tpu/client", status_factory(client_box)
        )
        peer = client.connect("127.0.0.1", port, privkey_to_pubkey(PRIV_A))
        managers.extend([server, client])
        return server, client, peer

    yield connect
    for m in managers:
        m.stop()


class TestRegularSync:
    def test_fresh_node_syncs_50_blocks_with_reorg(self, loopback):
        chain1 = build_chain(30)
        chain2 = build_chain(50, diverge_at=26)
        assert chain1[24].hash == chain2[24].hash  # shared prefix
        assert chain1[25].hash != chain2[25].hash  # divergence

        server_box = _NodeBox(make_serving_node(chain1))
        syncer_bc = Blockchain(Storages(), CFG)
        syncer_bc.load_genesis(GenesisSpec(alloc=ALLOC))
        client_box = _NodeBox(syncer_bc)
        server, client, peer = loopback(server_box, client_box)

        sync = RegularSyncService(syncer_bc, CFG, client, batch_size=7)

        # phase 1: catch up to the serving node's 30-block chain
        sync.run(until=lambda: syncer_bc.best_block_number >= 30,
                 max_seconds=60)
        assert syncer_bc.best_block_number == 30
        assert syncer_bc.get_hash_by_number(30) == chain1[-1].hash
        assert sync.reorgs == 0

        # phase 2: the peer switches to a longer (higher-TD) branch that
        # diverges at #26 — the syncer must roll back and adopt it
        server_box.bc = make_serving_node(chain2)
        sync.run(until=lambda: syncer_bc.best_block_number >= 50,
                 max_seconds=60)
        assert syncer_bc.best_block_number == 50
        assert syncer_bc.get_hash_by_number(50) == chain2[-1].hash
        assert syncer_bc.get_hash_by_number(26) == chain2[25].hash
        assert sync.reorgs == 1
        assert sync.imported >= 50 + 5  # 30 + 25 re-imported
        # the orphaned branch is gone from the canonical index
        assert syncer_bc.get_header_by_hash(chain1[-1].hash) is None

    def test_lower_td_branch_is_rejected(self, loopback):
        chain1 = build_chain(30)
        short_fork = build_chain(27, diverge_at=26)

        server_box = _NodeBox(make_serving_node(chain1))
        syncer_bc = Blockchain(Storages(), CFG)
        syncer_bc.load_genesis(GenesisSpec(alloc=ALLOC))
        client_box = _NodeBox(syncer_bc)
        server, client, peer = loopback(server_box, client_box)

        sync = RegularSyncService(syncer_bc, CFG, client, batch_size=7)
        sync.run(until=lambda: syncer_bc.best_block_number >= 30,
                 max_seconds=60)

        # peer switches to a SHORTER branch: its status TD is lower, so
        # the syncer must not move at all
        server_box.bc = make_serving_node(short_fork)
        assert sync.sync_once() == 0
        assert syncer_bc.best_block_number == 30
        assert syncer_bc.get_hash_by_number(30) == chain1[-1].hash
        assert sync.reorgs == 0

    def test_missing_node_heals_through_peer(self, loopback):
        chain = build_chain(12)
        server_box = _NodeBox(make_serving_node(chain))
        syncer_bc = Blockchain(Storages(), CFG)
        syncer_bc.load_genesis(GenesisSpec(alloc=ALLOC))
        client_box = _NodeBox(syncer_bc)
        server, client, peer = loopback(server_box, client_box)

        sync = RegularSyncService(syncer_bc, CFG, client, batch_size=4)
        sync.run(until=lambda: syncer_bc.best_block_number >= 8,
                 max_seconds=60)

        # vandalize: drop the current state root node from the syncer's
        # account store (cache + backing dict), as a crash/partial-write
        # would; the next import must heal it from the peer
        root = syncer_bc.get_header_by_number(8).state_root
        ns = syncer_bc.storages.account_node_storage
        ns._cache.remove(root)
        ns._unconfirmed.source._map.pop(root, None)
        dcache = getattr(ns, "_mpt_dcache", None)
        if dcache is not None:
            dcache.pop(root, None)

        sync.run(until=lambda: syncer_bc.best_block_number >= 12,
                 max_seconds=60)
        assert syncer_bc.best_block_number == 12
        assert sync.healed_nodes >= 1
        assert ns.get(root) is not None  # healed back into the store


class TestNewBlockPropagation:
    def test_pushed_block_imports_without_pull(self, loopback):
        """The push path (BroadcastNewBlocks role): a sealed block
        broadcast over NewBlock imports directly on the receiving node;
        no pull round involved."""
        from khipu_tpu.sync.regular_sync import broadcast_new_block

        chain = build_chain(6)
        server_box = _NodeBox(make_serving_node(chain[:5]))
        syncer_bc = Blockchain(Storages(), CFG)
        syncer_bc.load_genesis(GenesisSpec(alloc=ALLOC))
        client_box = _NodeBox(syncer_bc)
        server, client, peer = loopback(server_box, client_box)

        sync = RegularSyncService(syncer_bc, CFG, client, batch_size=5)
        sync.install_new_block_handler()
        sync.run(until=lambda: syncer_bc.best_block_number >= 5,
                 max_seconds=30)

        # the SERVER pushes block 6 to its peers (miner-broadcast role);
        # its inbound peer is the client's connection
        td = (server_box.bc.get_total_difficulty(5) or 0) + chain[5].header.difficulty
        sent = broadcast_new_block(server, chain[5], td)
        assert sent == 1
        deadline = time.time() + 10
        while syncer_bc.best_block_number < 6 and time.time() < deadline:
            time.sleep(0.05)
        assert syncer_bc.best_block_number == 6
        assert syncer_bc.get_hash_by_number(6) == chain[5].hash
        assert sync.imported == 6  # 5 pulled + 1 pushed


class TestShorterPeerChains:
    def test_stale_higher_td_shorter_peer_does_not_wedge(self, loopback):
        """A peer whose advertised TD is stale-high while its chain is
        SHORTER than ours: the forward fetch is empty, the downward
        probe finds its (prefix-identical) headers, and the round ends
        cleanly — no wedge, no bogus reorg, no blacklist."""
        chain = build_chain(30)
        # server knows only the first 20 blocks of OUR chain...
        server_box = _NodeBox(make_serving_node(chain[:20]))

        # ...but lies that it has more TD than anyone
        def lying_status():
            real = status_factory(server_box)()
            return dataclasses.replace(
                real, total_difficulty=real.total_difficulty * 100
            )

        syncer_bc = Blockchain(Storages(), CFG)
        syncer_bc.load_genesis(GenesisSpec(alloc=ALLOC))
        server = PeerManager(PRIV_A, "khipu-tpu/liar", lying_status)
        _SwitchingHost(server_box).install(server)
        port = server.listen()
        client = PeerManager(
            PRIV_B, "khipu-tpu/client", status_factory(_NodeBox(syncer_bc))
        )
        client.connect("127.0.0.1", port, privkey_to_pubkey(PRIV_A))
        try:
            sync = RegularSyncService(syncer_bc, CFG, client, batch_size=7)
            # catch up to the peer's 20 blocks first
            sync.run(until=lambda: syncer_bc.best_block_number >= 20,
                     max_seconds=30)
            # import the rest of OUR chain locally (we are now longer)
            ReplayDriver(syncer_bc, CFG).replay(chain[20:])
            assert syncer_bc.best_block_number == 30
            # rounds against the stale-TD shorter peer terminate with 0
            for _ in range(3):
                assert sync.sync_once() == 0
            assert syncer_bc.best_block_number == 30
            assert sync.reorgs == 0
            assert not client.blacklist.is_blacklisted(
                privkey_to_pubkey(PRIV_A)
            )
        finally:
            server.stop()
            client.stop()


class TestTxGossipAndAnnounces:
    def test_tx_gossip_mine_remove_both_pools(self, loopback):
        """The verdict-6 loop: a tx submitted on node A gossips to node
        B over SignedTransactions; B mines it; the NewBlock propagation
        imports it back on A — and the tx disappears from BOTH pools
        via the import-path remove_mined."""
        from khipu_tpu.sync.regular_sync import (
            gossip_pending,
            propagate_block,
        )
        from khipu_tpu.txpool import PendingTransactionsPool

        a_bc = make_serving_node([])
        b_bc = make_serving_node([])
        a_box, b_box = _NodeBox(a_bc), _NodeBox(b_bc)
        server, client, peer = loopback(a_box, b_box)

        a_pool = PendingTransactionsPool()
        b_pool = PendingTransactionsPool()
        a_sync = RegularSyncService(a_bc, CFG, server, txpool=a_pool)
        b_sync = RegularSyncService(b_bc, CFG, client, txpool=b_pool)
        # the server's inbound peer appears on its accept thread; wait
        # for it so the handler install + gossip below reach it
        deadline = time.time() + 10
        while not server.peers and time.time() < deadline:
            time.sleep(0.02)
        assert server.peers, "inbound peer never appeared"
        a_sync.install_new_block_handler()
        b_sync.install_new_block_handler()

        # 1. submit on A, gossip to B
        stx = sign_transaction(
            Transaction(0, 10**9, 21_000, b"\xd0" * 20, 5),
            SENDER_KEY, chain_id=1,
        )
        cursor = a_pool.cursor()
        a_pool.add(stx)
        gossip_pending(server, a_pool, cursor)
        deadline = time.time() + 10
        while len(b_pool) == 0 and time.time() < deadline:
            time.sleep(0.05)
        assert b_pool.get(stx.hash) is not None, "tx never gossiped to B"

        # 2. B mines the tx (builder plays the sealer) and imports it
        builder = ChainBuilder(
            make_serving_node([]), CFG, GenesisSpec(alloc=ALLOC)
        )
        block = builder.add_block([stx], coinbase=b"\xaa" * 20)
        with b_sync._import_lock:
            b_sync._on_new_block_locked(block)
        assert b_bc.best_block_number == 1
        assert len(b_pool) == 0, "miner-side remove_mined missed"

        # 3. B propagates; A imports and drops the tx from its pool
        td = (b_bc.get_total_difficulty(0) or 0) + block.header.difficulty
        assert propagate_block(client, block, td) == 1
        deadline = time.time() + 10
        while a_bc.best_block_number < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert a_bc.best_block_number == 1
        assert len(a_pool) == 0, "import-side remove_mined missed"

    def test_new_block_hashes_announce_fetch(self, loopback):
        """A NewBlockHashes announce (no full block) is queued by the
        handler and fetched + imported by the next pull tick."""
        from khipu_tpu.network.messages import (
            ETH_OFFSET,
            NEW_BLOCK_HASHES,
            encode_new_block_hashes,
        )

        chain = build_chain(3)
        server_box = _NodeBox(make_serving_node(chain))
        syncer_bc = Blockchain(Storages(), CFG)
        syncer_bc.load_genesis(GenesisSpec(alloc=ALLOC))
        client_box = _NodeBox(syncer_bc)
        server, client, peer = loopback(server_box, client_box)

        sync = RegularSyncService(syncer_bc, CFG, client, batch_size=5)
        sync.install_new_block_handler()
        sync.run(until=lambda: syncer_bc.best_block_number >= 2,
                 max_seconds=30)
        # roll the server's view back? no — announce block 3 by hash
        inbound = server.peers[0]
        inbound.send(
            ETH_OFFSET + NEW_BLOCK_HASHES,
            encode_new_block_hashes([(chain[2].hash, 3)]),
        )
        deadline = time.time() + 10
        while not sync._announced and time.time() < deadline:
            if syncer_bc.best_block_number >= 3:
                break
            time.sleep(0.02)
        # drain on the pull thread
        sync.run(until=lambda: syncer_bc.best_block_number >= 3,
                 max_seconds=20)
        assert syncer_bc.get_hash_by_number(3) == chain[2].hash


class TestAnnounceBacklogRequeue:
    """_drain_announces under _import_lock contention: the unprocessed
    tail must go BACK to the backlog (it used to be dropped on the
    floor when a push import held the lock)."""

    def _sync(self):
        bc = Blockchain(Storages(), CFG)
        bc.load_genesis(GenesisSpec(alloc=ALLOC))
        return RegularSyncService(bc, CFG, manager=None)

    def test_lock_contention_requeues_unprocessed_tail(self):
        import types

        sync = self._sync()
        genesis = sync.blockchain.get_header_by_number(0)
        h2, h3 = b"\x02" * 32, b"\x03" * 32
        pairs = [(genesis.hash, 5, None), (h2, 1, None), (h3, 1, None)]
        with sync._announce_lock:
            sync._announced.extend(pairs)
        sync._request_headers = lambda src, n, c: [
            types.SimpleNamespace(hash=h2)
        ]
        sync._fetch_blocks = lambda src, headers: ["sentinel"]
        peer = types.SimpleNamespace(alive=True)
        assert sync._import_lock.acquire(blocking=False)
        try:
            sync._drain_announces(peer)
        finally:
            sync._import_lock.release()
        assert sync.imported == 0
        # the already-known genesis announce is consumed; the announce
        # that hit the contended lock AND everything after it survive
        assert sync._announced == pairs[1:]

    def test_uncontended_drain_empties_backlog(self):
        import types

        sync = self._sync()
        with sync._announce_lock:
            sync._announced.append((b"\x09" * 32, 99, None))  # gap
        sync._drain_announces(types.SimpleNamespace(alive=True))
        assert sync._announced == []  # gaps are the pull round's job
