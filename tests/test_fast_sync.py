"""Fast-sync state download + checkpoint/resume + compactor tests
(parity targets FastSyncService.scala:100, FastSyncStateStorage.scala:24,
KesqueCompactor.scala:32, tools/DataChecker.scala:122)."""

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import (
    Transaction,
    contract_address,
    sign_transaction,
)
from khipu_tpu.storage.compactor import compact, verify_reachable
from khipu_tpu.storage.datasource import MemoryNodeDataSource
from khipu_tpu.storage.known_nodes import KnownNodesStorage
from khipu_tpu.storage.datasource import MemoryKeyValueDataSource
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.fast_sync import (
    FastSyncStateStorage,
    StateSyncer,
    SyncState,
)

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(4)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18

# contract with two storage slots AND deployed runtime code, so the
# sync crosses all three stores (state, storage, evmcode)
_RUNTIME = bytes.fromhex("60005460005260206000f3")
_SSTORES = bytes.fromhex("602a600055600b600155")
_COPY = bytes(
    [0x60, len(_RUNTIME), 0x60, len(_SSTORES) + 12, 0x60, 0x00, 0x39,
     0x60, len(_RUNTIME), 0x60, 0x00, 0xF3]
)
INIT = _SSTORES + _COPY + _RUNTIME


def build_source_chain():
    bc = Blockchain(Storages(), CFG)
    builder = ChainBuilder(
        bc, CFG, GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS})
    )
    builder.add_block(
        [sign_transaction(Transaction(0, 10**9, 200_000, None, 0, INIT), KEYS[0], chain_id=1)],
        coinbase=b"\xaa" * 20,
    )
    head = builder.add_block(
        [sign_transaction(Transaction(1, 10**9, 21_000, ADDRS[1], 5 * ETH), KEYS[0], chain_id=1)],
        coinbase=b"\xaa" * 20,
    )
    return bc, head


def make_fetch(source_storages):
    def fetch(hashes):
        out = {}
        for h in hashes:
            for store in (
                source_storages.account_node_storage,
                source_storages.storage_node_storage,
                source_storages.evmcode_storage,
            ):
                v = store.get(h)
                if v is not None:
                    out[h] = v
                    break
        return out

    return fetch


class TestStateSyncer:
    def test_full_state_download(self):
        src_bc, head = build_source_chain()
        root = head.header.state_root
        target = Storages()
        syncer = StateSyncer(
            target,
            FastSyncStateStorage(MemoryKeyValueDataSource()),
            make_fetch(src_bc.storages),
        )
        state = syncer.start(root)
        assert state.downloaded_nodes > 0
        assert target.app_state.fast_sync_done
        # the synced state is complete and readable
        report = verify_reachable(
            target.account_node_storage,
            target.storage_node_storage,
            target.evmcode_storage,
            root,
        )
        assert report.missing == 0
        assert report.storage_nodes > 0 and report.code_blobs > 0
        tgt_bc = Blockchain(target, CFG)
        assert tgt_bc.get_account(ADDRS[1], root).balance == 1005 * ETH
        caddr = contract_address(ADDRS[0], 0)
        world = tgt_bc.get_world_state(root)
        assert world.get_storage(caddr, 0) == 42
        assert world.get_storage(caddr, 1) == 11
        assert world.get_code(caddr) != b""

    def test_crash_resume(self):
        src_bc, head = build_source_chain()
        root = head.header.state_root
        target = Storages()
        state_store = FastSyncStateStorage(MemoryKeyValueDataSource())

        calls = {"n": 0}
        base_fetch = make_fetch(src_bc.storages)

        def crashing_fetch(hashes):
            calls["n"] += 1
            if calls["n"] == 2:
                raise ConnectionError("peer died")
            return base_fetch(hashes)

        syncer = StateSyncer(
            target, state_store, crashing_fetch,
            batch_size=4, checkpoint_every=1,
        )
        with pytest.raises(ConnectionError):
            syncer.start(root)
        checkpoint = state_store.get_sync_state()
        assert checkpoint is not None and checkpoint.downloaded_nodes > 0

        # resume from the persisted checkpoint (fresh syncer = restart)
        resumed = StateSyncer(
            target, state_store, base_fetch, batch_size=4
        )
        final = resumed.start(root)
        assert final.downloaded_nodes >= checkpoint.downloaded_nodes
        assert state_store.get_sync_state() is None  # purged on finish
        assert verify_reachable(
            target.account_node_storage,
            target.storage_node_storage,
            target.evmcode_storage,
            root,
        ).missing == 0

    def test_corrupt_node_rejected(self):
        src_bc, head = build_source_chain()
        root = head.header.state_root
        base_fetch = make_fetch(src_bc.storages)

        def corrupting_fetch(hashes):
            out = dict(base_fetch(hashes))
            for h in list(out)[:1]:
                out[h] = out[h] + b"\x00"  # content-address mismatch
            return out

        syncer = StateSyncer(
            Storages(),
            FastSyncStateStorage(MemoryKeyValueDataSource()),
            corrupting_fetch,
        )
        with pytest.raises(RuntimeError, match="no progress|unavailable"):
            syncer.start(root)

    def test_sync_state_codec(self):
        s = SyncState(b"\x11" * 32, [(0, b"\xaa" * 32), (2, b"\xbb" * 32)], 7)
        assert SyncState.decode(s.encode()) == s


class TestCompactor:
    def test_compact_copies_exactly_reachable(self):
        src_bc, head = build_source_chain()
        root = head.header.state_root
        dsts = [MemoryNodeDataSource() for _ in range(3)]
        report = compact(
            src_bc.storages.account_node_storage,
            src_bc.storages.storage_node_storage,
            src_bc.storages.evmcode_storage,
            root,
            *dsts,
        )
        assert report.missing == 0
        # the compacted generation serves the full state on its own
        again = verify_reachable(*dsts, root)
        assert again.missing == 0
        assert again.total == report.total
        # stale generations hold MORE nodes than the pivot needs
        # (superseded roots from earlier blocks stay in the archive)
        assert src_bc.storages.account_node_storage.source.count > report.state_nodes

    def test_verify_reachable_detects_loss(self):
        src_bc, head = build_source_chain()
        root = head.header.state_root
        # clone then delete one node from the clone's account store
        dsts = [MemoryNodeDataSource() for _ in range(3)]
        compact(
            src_bc.storages.account_node_storage,
            src_bc.storages.storage_node_storage,
            src_bc.storages.evmcode_storage,
            root,
            *dsts,
        )
        victim = next(iter(dsts[0]._map))
        del dsts[0]._map[victim]
        assert verify_reachable(*dsts, root).missing >= 1


class TestKnownNodes:
    def test_roundtrip(self):
        s = KnownNodesStorage(MemoryKeyValueDataSource())
        assert s.get_known_nodes() == set()
        s.update_known_nodes(to_add={"enode://a@1:30303", "enode://b@2:30303"})
        s.update_known_nodes(to_remove={"enode://a@1:30303"})
        assert s.get_known_nodes() == {"enode://b@2:30303"}

    def test_sync_with_device_mirror(self):
        """Verified nodes admit into the word-major device mirror at
        download time; completion re-verifies the WHOLE snapshot on
        resident tiles (config #5 integration)."""
        from khipu_tpu.storage.device_mirror import DeviceNodeMirror

        src_bc, head = build_source_chain()
        root = head.header.state_root
        target = Storages()
        mirror = DeviceNodeMirror(capacity_rows_per_class=1024)
        syncer = StateSyncer(
            target,
            FastSyncStateStorage(MemoryKeyValueDataSource()),
            make_fetch(src_bc.storages),
            mirror=mirror,
        )
        state = syncer.start(root)  # raises if snapshot verify fails
        assert mirror.resident_count > 0
        assert mirror.verify() == 0
        # the mirror's resident copy of the root matches the store
        assert mirror.get(root) == target.account_node_storage.get(root)
