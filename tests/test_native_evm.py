"""Differential suite: native C++ EVM vs the Python interpreter.

Strategy (SURVEY §4 model — oracle-based): the Python VM (itself pinned
by external vectors + mainnet anchors) is the oracle; every scenario
runs through BOTH backends on identical fresh worlds and must produce
identical results — status, gas, output, logs, refund, selfdestruct set
and the resulting state root. The GeneralStateTests fixture corpus is
replayed under the native backend too (it normally exercises whichever
backend dispatch picks).
"""

import random
import time

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.config import fixture_config
from khipu_tpu.domain.account import Account
from khipu_tpu.evm import dataword as dw
from khipu_tpu.evm import dispatch, native_vm
from khipu_tpu.evm.config import for_block
from khipu_tpu.evm.vm import BlockEnv, MessageEnv
from khipu_tpu.ledger.world import BlockWorldState
from khipu_tpu.storage.datasource import MemoryNodeDataSource
from khipu_tpu.trie.mpt import MerklePatriciaTrie

pytestmark = pytest.mark.skipif(
    not native_vm.available(), reason="native library not built"
)

CFG = for_block(1, fixture_config().blockchain)  # all forks active
FRONTIER = for_block(0, fixture_config(fork_block=10**9).blockchain)
OWNER = b"\xcc" * 20
CALLER = b"\xdd" * 20


# ------------------------------------------------------------- arithmetic

PY_OPS = {
    0: lambda a, b, c: (a + b) % dw.MOD,
    1: lambda a, b, c: (a - b) % dw.MOD,
    2: lambda a, b, c: (a * b) % dw.MOD,
    3: lambda a, b, c: a // b if b else 0,
    4: lambda a, b, c: a % b if b else 0,
    5: lambda a, b, c: dw.sdiv(a, b),
    6: lambda a, b, c: dw.smod(a, b),
    7: lambda a, b, c: pow(a, b, dw.MOD),
    8: lambda a, b, c: (a + b) % c if c else 0,
    9: lambda a, b, c: (a * b) % c if c else 0,
    10: lambda a, b, c: dw.signextend(a, b),
    11: lambda a, b, c: dw.byte_at(a, b),
    12: lambda a, b, c: (b << a) % dw.MOD if a < 256 else 0,
    13: lambda a, b, c: b >> a if a < 256 else 0,
    14: lambda a, b, c: dw.sar(a if a < 256 else 256, b),
}


def _interesting(rng):
    kind = rng.randrange(6)
    if kind == 0:
        return rng.getrandbits(256)
    if kind == 1:
        return rng.getrandbits(64)
    if kind == 2:
        return rng.getrandbits(8)
    if kind == 3:
        return (1 << 256) - 1 - rng.getrandbits(8)
    if kind == 4:
        return 1 << rng.randrange(256)
    return (1 << rng.randrange(1, 257)) - 1


def test_arith_differential_fuzz():
    rng = random.Random(0xC0FFEE)
    for _ in range(4000):
        op = rng.randrange(15)
        a, b, c = _interesting(rng), _interesting(rng), _interesting(rng)
        want = PY_OPS[op](a, b, c)
        got = native_vm.test_arith(op, a, b, c)
        assert got == want, f"op={op} a={a:x} b={b:x} c={c:x}"


def test_arith_edge_vectors():
    M = dw.MASK
    int_min = 1 << 255
    cases = [
        (5, int_min, M, 0),      # INT_MIN / -1 wraps to INT_MIN
        (6, int_min, M, 0),
        (3, 7, 0, 0), (4, 7, 0, 0), (8, 5, 6, 0), (9, 5, 6, 0),
        (7, 3, (1 << 256) - 1, 0),
        (10, 31, M, 0), (10, 500, 123, 0),
        (11, 32, 77, 0), (14, 256, int_min, 0), (14, 1, int_min, 0),
    ]
    for op, a, b, c in cases:
        assert native_vm.test_arith(op, a, b, c) == PY_OPS[op](a, b, c), (
            f"op={op} a={a:x} b={b:x}"
        )


# ------------------------------------------------------ message-level diff


def fresh_world():
    return BlockWorldState(
        MerklePatriciaTrie(MemoryNodeDataSource()),
        MemoryNodeDataSource(),
        MemoryNodeDataSource(),
    )


def _deploy(world, addr, code, balance=0, storage=()):
    world.save_account(addr, Account(nonce=0, balance=balance))
    if code:
        world.save_code(addr, code)
    for k, v in storage:
        world.save_storage(addr, k, v)
    # settle into the tries/sources so both backends read the same
    # committed base (incl. get_original_storage against the trie)
    world.persist(
        world.account_trie.source, world.storage_source,
        world.evmcode_source,
    )
    world.touched.clear()
    for cat in world.written:
        world.written[cat].clear()
    for cat in world.reads:
        world.reads[cat].clear()
    return world


def run_backend(backend, code, *, config=CFG, gas=1_000_000,
                input_data=b"", value=0, setup=None, pre_transfer=False):
    world = fresh_world()
    if setup:
        setup(world)
    _deploy(world, CALLER, b"", balance=10**18)
    env = MessageEnv(
        owner=OWNER, caller=CALLER, origin=CALLER, gas_price=1,
        value=value, input_data=input_data,
    )
    block = BlockEnv(1, 1000, 131072, 8_000_000, b"\xaa" * 20)
    dispatch.set_backend(backend)
    try:
        r = dispatch.run_message_call(
            config, world, block, env, code, gas, OWNER,
            pre_transfer=pre_transfer,
        )
    finally:
        dispatch.set_backend(None)
    return r, world


def assert_same(code, **kw):
    rp, wp = run_backend("python", code, **kw)
    rn, wn = run_backend("native", code, **kw)
    assert (rp.error is None) == (rn.error is None), (rp.error, rn.error)
    if rp.error is not None:
        assert rp.error.split(":")[0] == rn.error.split(":")[0], (
            rp.error, rn.error)
    assert rp.is_revert == rn.is_revert
    assert rp.gas_remaining == rn.gas_remaining, (
        f"gas {rp.gas_remaining} != {rn.gas_remaining} ({rp.error})"
    )
    assert rp.output == rn.output
    assert rp.refund == rn.refund
    assert [(l.address, l.topics, l.data) for l in rp.logs] == [
        (l.address, l.topics, l.data) for l in rn.logs
    ]
    if rp.ok:
        assert rp.world.root_hash == wn.root_hash
        assert set(rp.world.selfdestructed) == set(wn.selfdestructed)
    return rp, rn


def asm(*parts):
    out = b""
    for p in parts:
        out += bytes([p]) if isinstance(p, int) else p
    return out


def push(v, width=None):
    b = v.to_bytes(width, "big") if width else (
        v.to_bytes(max(1, (v.bit_length() + 7) // 8), "big"))
    return bytes([0x60 + len(b) - 1]) + b


class TestMessageDifferential:
    def test_arith_mstore_return(self):
        code = asm(push(2), push(3), 0x01, push(0), 0x52, push(32), push(0), 0xF3)
        assert_same(code)

    def test_storage_write_read_refund(self):
        # SSTORE 1->val, SSTORE ->0 (refund), SLOAD, return
        code = asm(
            push(0xAB), push(1), 0x55,        # s[1]=0xab
            push(0), push(1), 0x55,           # s[1]=0 (refund)
            push(7), push(2), 0x55,           # s[2]=7
            push(2), 0x54, push(0), 0x52, push(32), push(0), 0xF3,
        )
        for cfg in (CFG, FRONTIER):
            assert_same(code, config=cfg)

    def test_sstore_with_prestate(self):
        def setup(w):
            _deploy(w, OWNER, b"", storage=[(1, 99), (2, 5)])
        # dirty-write paths of EIP-2200: 99->0->99, 5->7
        code = asm(
            push(0), push(1), 0x55, push(99), push(1), 0x55,
            push(7), push(2), 0x55, 0x00,
        )
        for cfg in (CFG, FRONTIER):
            assert_same(code, config=cfg, setup=setup)

    def test_env_ops_and_sha3(self):
        code = asm(
            0x30, 0x31, 0x01,            # ADDRESS BALANCE ADD
            0x32, 0x33, 0x01, 0x01,      # ORIGIN CALLER
            0x34, 0x3A, 0x01, 0x01,      # CALLVALUE GASPRICE
            0x41, 0x42, 0x43, 0x44, 0x45, 0x01, 0x01, 0x01, 0x01, 0x01,
            0x46, 0x47, 0x01, 0x01,      # CHAINID SELFBALANCE
            push(0), 0x52,
            push(8), push(3), 0x20,      # SHA3 over memory[3:11]
            push(0), 0x52, push(32), push(0), 0xF3,
        )
        assert_same(code, value=5, pre_transfer=True)

    def test_calldata_code_copies(self):
        code = asm(
            push(10), push(3), push(0), 0x37,   # CALLDATACOPY
            0x36, push(0), 0x52,                # CALLDATASIZE
            push(20), push(5), push(64), 0x39,  # CODECOPY
            push(96), push(0), 0xF3,
        )
        assert_same(code, input_data=bytes(range(1, 30)))

    def test_copy_src_offset_wraparound(self):
        # src near 2^64 must zero-pad, not wrap src+i back into the
        # buffer (consensus-divergence regression: u64 overflow guard)
        huge = (1 << 64) - 1
        for copy_op in (0x37, 0x39):  # CALLDATACOPY, CODECOPY
            code = asm(
                push(4), push(huge, 8), push(0), copy_op,
                push(2), push(1 << 200, 26), push(8), copy_op,
                push(32), push(0), 0xF3,
            )
            assert_same(code, input_data=b"\xab" * 64)
        other = b"\x29" * 20
        code = asm(
            push(4), push(huge, 8), push(0),
            push(int.from_bytes(other, "big"), 20), 0x3C,  # EXTCODECOPY
            push(32), push(0), 0xF3,
        )
        assert_same(code, setup=lambda w: _deploy(w, other, b"\xcd" * 40))

    def test_blockhash_oob(self):
        code = asm(push(0), 0x40, push(500), 0x40, 0x01, push(0), 0x52,
                   push(32), push(0), 0xF3)
        assert_same(code)

    def test_exp_gas(self):
        code = asm(push(3), push(2), 0x0A, push(0x1234, 2), push(2), 0x0A,
                   0x01, push(0), 0x52, push(32), push(0), 0xF3)
        for cfg in (CFG, FRONTIER):
            assert_same(code, config=cfg)

    def test_oog_mid_program(self):
        code = asm(push(1), push(1), 0x55, 0x00)
        assert_same(code, gas=5_000)  # not enough for SSTORE

    def test_invalid_jump(self):
        assert_same(asm(push(3), 0x56, 0x00))

    def test_jump_loop(self):
        # countdown loop: 10 iterations then stop
        code = asm(
            push(10),                      # counter
            0x5B,                          # JUMPDEST @ pc=2
            push(1), 0x90, 0x03,           # c-1
            0x80, push(2), 0x57,           # JUMPI back while nonzero
            0x00,
        )
        assert_same(code)

    def test_stack_underflow_overflow(self):
        assert_same(asm(0x01))  # underflow
        assert_same(asm(*([push(1)] * 3), 0x80 + 4))  # DUP5 underflow

    def test_revert_and_returndata(self):
        inner = asm(push(0xEE), push(0), 0x52, push(32), push(0), 0xFD)
        inner_addr = b"\x11" * 20

        def setup(w):
            _deploy(w, inner_addr, inner)

        code = asm(
            push(0), push(0), push(0), push(0), push(0),
            push(int.from_bytes(inner_addr, "big"), 20), push(50_000),
            0xF1,                          # CALL -> reverts
            0x3D,                          # RETURNDATASIZE
            push(0), 0x52,
            push(32), push(0), push(0), 0x3E,  # RETURNDATACOPY @32... wait
            0x00,
        )
        assert_same(code, setup=setup)

    def test_memory_expansion_quadratic_oog(self):
        code = asm(push(1), push(1 << 30, 5), 0x52, 0x00)
        assert_same(code, gas=100_000)

    def test_msize_pc_gas(self):
        code = asm(0x58, 0x59, 0x5A, 0x01, 0x01, push(0), 0x52, push(32),
                   push(0), 0xF3)
        assert_same(code)

    def test_logs(self):
        code = asm(
            push(0xAA), push(0), 0x52,
            push(1), push(2), push(16), push(8), 0xA2,  # LOG2
            push(3), push(0), push(0), 0xA1,            # LOG1 empty data
            0x00,
        )
        rp, rn = assert_same(code)
        assert len(rp.logs) == 2

    def test_shifts_and_extcode(self):
        other = b"\x22" * 20
        other_code = asm(push(1), 0x00)

        def setup(w):
            _deploy(w, other, other_code)

        w = int.from_bytes(other, "big")
        code = asm(
            push(w, 20), 0x3B,            # EXTCODESIZE
            push(4), push(1), push(0), push(w, 20), 0x3C,  # EXTCODECOPY
            push(w, 20), 0x3F,            # EXTCODEHASH
            push(0xDEAD, 2), push(2), 0x1B,  # SHL
            push(3), 0x1C, 0x01, 0x01,
            push(0), 0x52, push(32), push(0), 0xF3,
        )
        assert_same(code, setup=setup)


class TestCallCreateDifferential:
    def _counter(self):
        # increments its own slot 0 and returns the new value
        return asm(push(0), 0x54, push(1), 0x01, 0x80, push(0), 0x55,
                   push(0), 0x52, push(32), push(0), 0xF3)

    def test_call_with_value_and_storage(self):
        target = b"\x33" * 20

        def setup(w):
            _deploy(w, target, self._counter())
            _deploy(w, OWNER, b"", balance=10**9)

        t = int.from_bytes(target, "big")
        code = asm(
            push(32), push(0), push(0), push(0), push(77), push(t, 20),
            push(100_000, 3), 0xF1,
            push(32), push(0), push(0), push(0), push(0), push(t, 20),
            push(100_000, 3), 0xF1,
            0x01, push(0), 0x52, push(64), push(0), 0xF3,
        )
        assert_same(code, setup=setup)

    def test_callcode_delegatecall_static(self):
        target = b"\x44" * 20

        def setup(w):
            _deploy(w, target, self._counter())
            _deploy(w, OWNER, b"", balance=10**9)

        t = int.from_bytes(target, "big")
        code = asm(
            # CALLCODE: counter runs in OUR storage
            push(32), push(0), push(0), push(0), push(0), push(t, 20),
            push(100_000, 3), 0xF2,
            # DELEGATECALL: same
            push(32), push(32), push(0), push(0), push(t, 20),
            push(100_000, 3), 0xF4,
            # STATICCALL to the counter must FAIL (SSTORE in static)
            push(32), push(64), push(0), push(0), push(t, 20),
            push(100_000, 3), 0xFA,
            0x01, 0x01,
            push(0), 0x52, push(96), push(0), 0xF3,
        )
        assert_same(code, setup=setup)

    def test_call_to_missing_and_precompiles(self):
        dead = b"\x55" * 20
        code = asm(
            # value call to a nonexistent account (G_newaccount path)
            push(0), push(0), push(0), push(0), push(5),
            push(int.from_bytes(dead, "big"), 20), push(100_000, 3), 0xF1,
            # identity precompile
            push(4), push(0), 0x37,
            push(32), push(0), push(4), push(0), push(0), push(4),
            push(30_000, 2), 0xF1,
            # sha256 precompile
            push(32), push(32), push(4), push(0), push(0), push(2),
            push(30_000, 2), 0xF1,
            0x01, 0x01, push(0), 0x52, push(64), push(0), 0xF3,
        )

        def setup(w):
            _deploy(w, OWNER, b"", balance=10**9)

        assert_same(code, setup=setup, input_data=b"\xde\xad\xbe\xef")

    def test_depth_limited_recursion(self):
        # contract calls itself until depth/gas exhaustion
        me = int.from_bytes(OWNER, "big")
        code = asm(
            push(0), push(0), push(0), push(0), push(0), push(me, 20),
            0x5A, 0xF1, 0x00,
        )
        assert_same(code, gas=300_000)

    def test_create_and_create2(self):
        # init code returning a 2-byte runtime
        runtime = asm(push(7), push(0), 0x52, push(32), push(0), 0xF3)
        init = asm(
            push(int.from_bytes(runtime, "big"), len(runtime)),
            push(0), 0x52,
            push(len(runtime)), push(32 - len(runtime)), 0xF3,
        )
        def setup(w):
            _deploy(w, OWNER, b"", balance=10**9)

        store_init = asm(push(int.from_bytes(init, "big"), len(init)),
                         push(0), 0x52)
        code = asm(
            store_init,
            push(len(init)), push(32 - len(init)), push(3), 0xF0,   # CREATE
            push(0x5A17, 2),
            push(len(init)), push(32 - len(init)), push(0), 0xF5,   # CREATE2
            0x01, push(0), 0x52, push(32), push(0), 0xF3,
        )
        assert_same(code, setup=setup)

    def test_create_failure_paths(self):
        def setup(w):
            _deploy(w, OWNER, b"", balance=10**9)
        # init code reverts
        init_rev = asm(push(0), push(0), 0xFD)
        code = asm(
            push(int.from_bytes(init_rev, "big"), len(init_rev)),
            push(0), 0x52,
            push(len(init_rev)), push(32 - len(init_rev)), push(0), 0xF0,
            0x15, push(0), 0x52, push(32), push(0), 0xF3,
        )
        assert_same(code, setup=setup)
        # init code OOGs
        init_oog = asm(push(1), push(1), 0x55)
        code2 = asm(
            push(int.from_bytes(init_oog, "big"), len(init_oog)),
            push(0), 0x52,
            push(len(init_oog)), push(32 - len(init_oog)), push(0), 0xF0,
            0x15, push(0), 0x52, push(32), push(0), 0xF3,
        )
        assert_same(code2, setup=setup, gas=80_000)

    def test_selfdestruct(self):
        ben = b"\x66" * 20

        def setup(w):
            _deploy(w, OWNER, b"", balance=12345)

        code = asm(push(int.from_bytes(ben, "big"), 20), 0xFF)
        for cfg in (CFG, FRONTIER):
            assert_same(code, setup=setup, config=cfg)

    def test_selfdestruct_to_self(self):
        def setup(w):
            _deploy(w, OWNER, b"", balance=999)
        code = asm(push(int.from_bytes(OWNER, "big"), 20), 0xFF)
        assert_same(code, setup=setup)

    def test_nested_revert_rolls_back_inner_sstore(self):
        inner_addr = b"\x77" * 20
        # inner: SSTORE then REVERT
        inner = asm(push(5), push(0), 0x55, push(0), push(0), 0xFD)

        def setup(w):
            _deploy(w, inner_addr, inner)
            _deploy(w, OWNER, b"", balance=10**9)

        code = asm(
            push(1), push(1), 0x55,  # our own write survives
            push(0), push(0), push(0), push(0), push(0),
            push(int.from_bytes(inner_addr, "big"), 20),
            push(100_000, 3), 0xF1,
            push(0), 0x52, push(32), push(0), 0xF3,
        )
        assert_same(code, setup=setup)


# ------------------------------------------------------- bytecode fuzzing


def _random_program(rng):
    """PUSH-biased random programs: mostly valid-ish sequences with
    arithmetic/memory/flow ops, occasionally garbage bytes."""
    ops = ([0x01, 0x02, 0x03, 0x04, 0x05, 0x06, 0x07, 0x08, 0x09, 0x0A,
            0x0B, 0x10, 0x11, 0x12, 0x13, 0x14, 0x15, 0x16, 0x17, 0x18,
            0x19, 0x1A, 0x1B, 0x1C, 0x1D, 0x20, 0x30, 0x31, 0x32, 0x33,
            0x34, 0x35, 0x36, 0x38, 0x3A, 0x3B, 0x41, 0x42, 0x43, 0x44,
            0x45, 0x46, 0x47, 0x50, 0x51, 0x52, 0x53, 0x54, 0x55, 0x56,
            0x57, 0x58, 0x59, 0x5A, 0x5B] +
           list(range(0x80, 0x90)) + list(range(0x90, 0xA0)))
    out = b""
    for _ in range(rng.randrange(5, 60)):
        r = rng.random()
        if r < 0.45:
            n = rng.randrange(1, 5)
            out += bytes([0x60 + n - 1]) + rng.randbytes(n)
        elif r < 0.92:
            out += bytes([rng.choice(ops)])
        else:
            out += bytes([rng.randrange(256)])
    out += bytes([rng.choice([0x00, 0xF3, 0xFD])])
    if out[-1] in (0xF3, 0xFD):
        out = push(32) + push(0) + out
    return out


def test_random_bytecode_differential():
    rng = random.Random(20260730)
    for i in range(300):
        code = _random_program(rng)
        try:
            assert_same(code, gas=200_000)
        except AssertionError as e:
            raise AssertionError(f"program #{i} {code.hex()}") from e


# -------------------------------------------------- statetest corpus


def test_statetest_corpus_under_native_backend():
    import glob
    import os

    from khipu_tpu.statetest import run_file

    files = sorted(glob.glob(os.path.join(
        os.path.dirname(__file__), "fixtures", "state_tests", "*.json")))
    assert files
    dispatch.set_backend("native")
    try:
        for path in files:
            for r in run_file(path):
                assert r.ok, f"{path}: {r.name}[{r.fork}]{r.index} {r.detail}"
    finally:
        dispatch.set_backend(None)


# ----------------------------------------------- wall-clock parallelism


def test_native_interpretation_releases_the_gil():
    """The property behind the reference's multicore claim
    (TxProcessor.scala:28-49): while a native frame interprets, other
    Python threads must keep running. This CI box has ONE core, so a
    wall-clock speedup is unmeasurable here — instead verify the GIL is
    actually released: a Python spinner thread must keep making progress
    during a long native call (if the .so held the GIL, the spinner
    would freeze for the whole call)."""
    import threading

    # tight 300k-iteration loop of MULMOD work (~tens of ms per frame)
    code = asm(
        push(300_000, 3),
        0x5B,                                    # JUMPDEST @4
        push(3), 0x80, 0x80, 0x09, 0x50,         # mulmod churn
        push(1), 0x90, 0x03,
        0x80, push(4), 0x57,
        0x00,
    )

    def one():
        r, _ = run_backend("native", code, gas=50_000_000)
        assert r.ok

    one()  # warm (build, caches)

    counter = [0]
    stop = threading.Event()

    def spin():
        c = 0
        while not stop.is_set():
            c += 1
            if c % 1024 == 0:
                counter[0] = c
        counter[0] = c

    # spinner alone for the same duration as the native run
    t0 = time.perf_counter()
    one()
    native_s = time.perf_counter() - t0

    th = threading.Thread(target=spin)
    th.start()
    time.sleep(native_s)
    alone = counter[0]
    t0 = time.perf_counter()
    one()
    during_window = time.perf_counter() - t0
    stop.set()
    th.join()
    during = counter[0] - alone
    # normalize rates; GIL held => `during` collapses to ~0
    rate_alone = alone / native_s
    rate_during = during / during_window
    assert rate_during > 0.25 * rate_alone, (
        f"spinner starved during native call: {rate_during:.0f}/s vs "
        f"{rate_alone:.0f}/s alone — GIL not released?"
    )
