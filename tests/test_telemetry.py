"""Cluster telemetry plane (the PR-10 tentpole): GetMetrics federation
codec, shard-labeled merge semantics, per-shard health scoring feeding
admission, and the pipeline stall watchdog
(khipu_tpu/observability/telemetry.py — docs/observability.md).

The headline scenarios: a 2-shard bridge cluster whose merged
exposition carries ``shard`` labels under one TYPE line per family;
killing a shard drives ``khipu_shard_up`` to 0 and the health score
under the threshold within ONE scrape, the cluster-pressure admission
signal sheds writes (with ``cluster`` blamed), and a healed shard
restores admission; a chaos-injected ``collector.persist`` latency
trips ``watchdog.stage_stall`` into the chrome trace while a clean run
— and a 120-seed synthetic gauge sweep — trips NOTHING.
"""

import dataclasses
import threading
import time
from random import Random

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.chaos import FaultPlan, FaultRule, active
from khipu_tpu.config import (
    ServingConfig,
    SyncConfig,
    TelemetryConfig,
    fixture_config,
)
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.observability import export
from khipu_tpu.observability.registry import MetricsRegistry
from khipu_tpu.observability.telemetry import (
    WATCHDOG_KINDS,
    ClusterTelemetry,
    HealthScore,
    Watchdog,
    decode_metrics,
    encode_metrics,
)
from khipu_tpu.observability.trace import Tracer
from khipu_tpu.serving import ServerBusy
from khipu_tpu.serving.admission import (
    AdmissionController,
    cluster_pressure,
)
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.replay import PIPELINE_GAUGES, ReplayDriver

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(3)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ALLOC = {a: 10**21 for a in ADDRS}


# ----------------------------------------------------------- test rigs


class FakeMetricsClient:
    """In-process stand-in for BridgeClient.get_metrics: serves a real
    registry THROUGH the wire codec, with scripted failures."""

    def __init__(self, registry):
        self.registry = registry
        self.fail = False
        self.closed = False
        self.calls = 0

    def get_metrics(self):
        self.calls += 1
        if self.fail:
            raise ConnectionError("shard down")
        return decode_metrics(encode_metrics(self.registry))

    def close(self):
        self.closed = True


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _shard_registry(inflight=0):
    reg = MetricsRegistry()
    reg.gauge("khipu_pipeline_in_flight").set(inflight)
    return reg


def _telemetry(shards, clock=None, cluster=None, **cfg_kw):
    """ClusterTelemetry over FakeMetricsClient shards, on a private
    driver registry and (by default) a controlled clock."""
    cfg_kw.setdefault("enabled", True)
    cfg_kw.setdefault("scrape_interval", 1.0)
    cfg_kw.setdefault("staleness_s", 3.0)
    tel = ClusterTelemetry(
        list(shards),
        config=TelemetryConfig(**cfg_kw),
        client_factory=lambda ep: shards[ep],
        cluster=cluster,
        registry=MetricsRegistry(),
        clock=clock or FakeClock(),
    )
    return tel


# ----------------------------------------------------------------- codec


class TestMetricsCodec:
    def test_round_trip_is_families(self):
        """decode(encode(r)) == r.families() — counters, labeled
        gauges, histograms; the merged view renders from the exact
        shape a local registry would."""
        r = MetricsRegistry()
        r.counter("khipu_reqs_total", help="requests").inc(7)
        r.gauge("khipu_depth", labels={"stage": "persist"}).set(3)
        h = r.histogram(
            "khipu_lat_seconds", buckets=(0.01, 0.1, 1.0)
        )
        h.observe(0.05)
        h.observe(0.5)
        assert decode_metrics(encode_metrics(r)) == r.families()

    def test_histogram_bucket_keys_stay_floats(self):
        """Bucket bounds ride through JSON as strings; the decoder
        must restore float ``le`` keys or merged rendering diverges
        from local rendering."""
        r = MetricsRegistry()
        r.histogram("khipu_h", buckets=(0.5, 2.0)).observe(1.0)
        fams = decode_metrics(encode_metrics(r))
        _kind, _help, samples = fams["khipu_h"]
        buckets = samples[0][1]["buckets"]
        assert all(isinstance(k, float) for k in buckets)
        assert buckets == {0.5: 0, 2.0: 1}

    def test_hostile_label_values_survive(self):
        hostile = 'a\\b"c\nd'
        r = MetricsRegistry()
        r.gauge("khipu_g", labels={"ep": hostile}).set(1.5)
        fams = decode_metrics(encode_metrics(r))
        assert fams["khipu_g"][2] == [({"ep": hostile}, 1.5)]

    def test_empty_registry(self):
        assert decode_metrics(encode_metrics(MetricsRegistry())) == {}


# ----------------------------------------------------------------- merge


class TestMergedExposition:
    def test_shard_labels_and_one_type_line(self):
        shards = {
            "a:1": FakeMetricsClient(_shard_registry(2)),
            "b:1": FakeMetricsClient(_shard_registry(5)),
        }
        tel = _telemetry(shards)
        assert tel.scrape_once() == 2
        fams = tel.merged_families()
        samples = dict(
            (lb["shard"], v)
            for lb, v in fams["khipu_pipeline_in_flight"][2]
        )
        assert samples == {"a:1": 2, "b:1": 5}  # per-shard, NOT summed
        text = tel.cluster_text()
        lines = text.splitlines()
        assert lines.count(
            "# TYPE khipu_pipeline_in_flight gauge"
        ) == 1
        assert 'khipu_pipeline_in_flight{shard="a:1"} 2' in lines
        assert 'khipu_pipeline_in_flight{shard="b:1"} 5' in lines

    def test_aligned_histograms_sum_bucketwise(self):
        regs = {}
        for ep, vals in (("a:1", (0.05,)), ("b:1", (0.5, 0.05))):
            reg = MetricsRegistry()
            h = reg.histogram("khipu_lat", buckets=(0.1, 1.0))
            for v in vals:
                h.observe(v)
            regs[ep] = reg
        tel = _telemetry(
            {ep: FakeMetricsClient(r) for ep, r in regs.items()}
        )
        tel.scrape_once()
        fams = tel.merged_families()
        samples = fams["khipu_lat"][2]
        assert len(samples) == 1  # ONE merged family, unlabeled
        labels, v = samples[0]
        assert "shard" not in labels
        assert v["count"] == 3
        assert v["sum"] == pytest.approx(0.6)
        assert v["buckets"] == {0.1: 2, 1.0: 3}
        assert tel.bucket_mismatches == 0

    def test_mismatched_buckets_degrade_per_shard(self):
        """Different bounds: summing would lie about the distribution
        — degrade to shard-labeled series and count the mismatch."""
        regs = {}
        for ep, bounds in (("a:1", (0.1, 1.0)), ("b:1", (0.5, 2.0))):
            reg = MetricsRegistry()
            reg.histogram("khipu_lat", buckets=bounds).observe(0.3)
            regs[ep] = reg
        tel = _telemetry(
            {ep: FakeMetricsClient(r) for ep, r in regs.items()}
        )
        tel.scrape_once()
        fams = tel.merged_families()
        shards = sorted(lb["shard"] for lb, _ in fams["khipu_lat"][2])
        assert shards == ["a:1", "b:1"]
        assert tel.bucket_mismatches == 1
        # ... and the driver registry exports the counter
        text = tel.registry.prometheus_text()
        assert "khipu_telemetry_bucket_mismatch_total 1" in text

    def test_stale_shard_ages_out(self):
        """A shard whose last good scrape exceeds staleness_s stops
        contributing samples — stale truth is worse than absence."""
        clock = FakeClock()
        shards = {
            "a:1": FakeMetricsClient(_shard_registry(1)),
            "b:1": FakeMetricsClient(_shard_registry(9)),
        }
        tel = _telemetry(shards, clock=clock, staleness_s=3.0)
        tel.scrape_once()  # both good at t=0
        shards["b:1"].fail = True
        clock.t = 2.0
        tel.scrape_once()  # a refreshed, b's families stay from t=0
        in_flight = {
            lb["shard"]
            for lb, _ in tel.merged_families()[
                "khipu_pipeline_in_flight"
            ][2]
        }
        assert in_flight == {"a:1", "b:1"}  # b stale-but-within-limit
        clock.t = 4.0  # b's data now 4s old > 3s staleness; a's 2s
        in_flight = {
            lb["shard"]
            for lb, _ in tel.merged_families()[
                "khipu_pipeline_in_flight"
            ][2]
        }
        assert in_flight == {"a:1"}


# ---------------------------------------------------------------- health


class TestHealthScore:
    def test_healthy_fresh_shard_scores_one(self):
        clock = FakeClock()
        tel = _telemetry(
            {"a:1": FakeMetricsClient(_shard_registry())}, clock=clock
        )
        tel.scrape_once()
        hs = tel.health_scores()["a:1"]
        assert hs.score == 1.0
        assert hs.components == {
            "freshness": 1.0, "breaker": 1.0,
            "errors": 1.0, "latency": 1.0,
        }
        assert tel.pressure() == 0.0  # exactly — the weights sum to 1

    def test_never_scraped_is_optimistic(self):
        """Starting the plane must never shed traffic by itself."""
        tel = _telemetry({"a:1": FakeMetricsClient(_shard_registry())})
        assert tel.health_scores()["a:1"].score == 1.0
        assert tel.pressure() == 0.0

    def test_unreachable_scores_zero_within_one_scrape(self):
        shard = FakeMetricsClient(_shard_registry())
        tel = _telemetry({"a:1": shard})
        tel.scrape_once()
        shard.fail = True
        tel.scrape_once()  # ONE failed scrape is enough
        hs = tel.health_scores()["a:1"]
        assert hs.score == 0.0
        assert tel.pressure() == 1.0
        rep = tel.report()["shards"]["a:1"]
        assert rep["up"] is False and rep["degraded"] is True
        assert "ConnectionError" in rep["lastError"]

    def test_freshness_decays_linearly_to_staleness(self):
        clock = FakeClock()
        tel = _telemetry(
            {"a:1": FakeMetricsClient(_shard_registry())},
            clock=clock, scrape_interval=1.0, staleness_s=3.0,
        )
        tel.scrape_once()
        clock.t = 1.0  # within one interval: still perfectly fresh
        assert tel.health_scores()["a:1"].score == 1.0
        clock.t = 2.0  # halfway from interval to staleness
        hs = tel.health_scores()["a:1"]
        assert hs.components["freshness"] == pytest.approx(0.5)
        assert hs.score == pytest.approx(0.8)  # 0.4*0.5 + 0.3+0.2+0.1
        clock.t = 3.0  # at staleness: freshness fully gone
        assert tel.health_scores()["a:1"].score == pytest.approx(0.6)

    def test_breaker_state_feeds_the_score(self):
        class _Breaker:
            def __init__(self, state):
                self.state = state

        class _Cluster:
            breakers = {"a:1": _Breaker("open")}

        clock = FakeClock()
        tel = _telemetry(
            {"a:1": FakeMetricsClient(_shard_registry())},
            clock=clock, cluster=_Cluster(),
        )
        tel.scrape_once()
        hs = tel.health_scores()["a:1"]
        assert hs.components["breaker"] == 0.0
        assert hs.score == pytest.approx(0.7)  # 0.4 + 0 + 0.2 + 0.1
        _Cluster.breakers["a:1"].state = "half-open"
        assert tel.health_scores()["a:1"].score == pytest.approx(0.85)

    def test_recovery_climbs_back_above_threshold(self):
        shard = FakeMetricsClient(_shard_registry())
        tel = _telemetry({"a:1": shard}, health_threshold=0.5)
        tel.scrape_once()
        shard.fail = True
        tel.scrape_once()
        assert tel.pressure() == 1.0
        shard.fail = False
        tel.scrape_once()
        hs = tel.health_scores()["a:1"]
        # errors component remembers the blip (2/3 of recent attempts
        # succeeded) but the shard is comfortably healthy again
        assert hs.components["errors"] == pytest.approx(2 / 3)
        assert hs.score > 0.9
        assert tel.report()["shards"]["a:1"]["degraded"] is False

    def test_report_key_gauges_and_registry_exports(self):
        shard = FakeMetricsClient(_shard_registry(inflight=4))
        tel = _telemetry(
            {"a:1": shard},
            key_gauges=("khipu_pipeline_in_flight",),
        )
        tel.scrape_once()
        rep = tel.report()
        assert rep["shards"]["a:1"]["keyGauges"] == {
            "khipu_pipeline_in_flight": 4
        }
        assert rep["scrapes"] == 1 and rep["scrapeFailures"] == 0
        text = tel.registry.prometheus_text()
        assert 'khipu_shard_health{endpoint="a:1"} 1.0' in text
        assert "khipu_telemetry_scrapes_total 1" in text

    def test_admission_sheds_writes_on_cluster_pressure(self):
        """The ROADMAP seam: worst-shard unhealth wired straight into
        the admission controller — writes shed with ``cluster``
        blamed, cheap reads keep flowing."""
        shard = FakeMetricsClient(_shard_registry())
        tel = _telemetry({"a:1": shard})
        tel.scrape_once()
        adm = AdmissionController(
            ServingConfig(), signals=[cluster_pressure(tel)],
            registry=MetricsRegistry(),
        )
        ticket = adm.acquire("eth_sendRawTransaction")  # healthy: in
        adm.release(ticket)
        shard.fail = True
        tel.scrape_once()
        with pytest.raises(ServerBusy, match="signal cluster"):
            adm.acquire("eth_sendRawTransaction")
        assert adm.shed_by_signal == {"cluster": 1}
        # cheap class never sheds on pressure (threshold > 1)
        adm.release(adm.acquire("eth_chainId"))
        snap = adm.snapshot()
        assert snap["pressureBySignal"]["cluster"] == 1.0
        assert snap["shedBySignal"] == {"cluster": 1}


# ---------------------------------------------------------- poller thread


class TestPoller:
    def test_background_scrapes_and_clean_stop(self):
        shard = FakeMetricsClient(_shard_registry())
        tel = ClusterTelemetry(
            ["a:1"],
            config=TelemetryConfig(
                enabled=True, scrape_interval=0.02, staleness_s=1.0
            ),
            client_factory=lambda ep: shard,
            registry=MetricsRegistry(),
        )
        tel.start()
        tel.start()  # idempotent
        try:
            deadline = time.time() + 5
            while shard.calls < 2 and time.time() < deadline:
                time.sleep(0.01)
            assert shard.calls >= 2
        finally:
            tel.stop()
        assert shard.closed
        before = shard.calls
        time.sleep(0.08)
        assert shard.calls == before  # the thread is really gone

    def test_failing_shard_never_kills_the_poller(self):
        shard = FakeMetricsClient(_shard_registry())
        shard.fail = True
        tel = ClusterTelemetry(
            ["a:1"],
            config=TelemetryConfig(
                enabled=True, scrape_interval=0.02, staleness_s=1.0
            ),
            client_factory=lambda ep: shard,
            registry=MetricsRegistry(),
        )
        tel.start()
        try:
            deadline = time.time() + 5
            while shard.calls < 3 and time.time() < deadline:
                time.sleep(0.01)
            assert shard.calls >= 3  # kept polling through failures
        finally:
            tel.stop()
        assert tel.scrape_failures >= 3


# -------------------------------------------------------------- watchdog


def _dog(gauges, clock=None, telemetry=None, tracer=None, **cfg_kw):
    cfg_kw.setdefault("enabled", True)
    cfg_kw.setdefault("stall_after_s", 5.0)
    cfg_kw.setdefault("journal_runaway_depth", 8)
    return Watchdog(
        config=TelemetryConfig(**cfg_kw),
        pipeline=gauges,
        journal_depth=gauges.pop("_journal", None),
        telemetry=telemetry,
        tracer=tracer,
        registry=MetricsRegistry(),
        clock=clock or FakeClock(),
    )


class TestWatchdogUnit:
    def test_stall_trips_once_and_rearms_on_progress(self):
        g = {"stage_persist_depth": 1, "stage_persist_busy_s": 2.0}
        dog = _dog(dict(g), stall_after_s=5.0)
        assert dog.check_once(now=0.0) == []  # arming observation
        assert dog.check_once(now=4.0) == []  # not stalled long enough
        assert dog.check_once(now=5.0) == ["stage_stall"]
        assert dog.check_once(now=20.0) == []  # edge-triggered: once
        assert dog.trips["stage_stall"] == 1
        kind, tags = dog.events[-1]
        assert kind == "stage_stall" and tags["stage"] == "persist"
        # progress (busy_s advanced) re-arms the detector
        dog._pipeline["stage_persist_busy_s"] = 2.5
        assert dog.check_once(now=21.0) == []
        dog._pipeline["stage_persist_busy_s"] = 2.5  # flat again
        assert dog.check_once(now=27.0) == ["stage_stall"]
        assert dog.trips["stage_stall"] == 2

    def test_empty_or_busy_stage_never_trips(self):
        dog = _dog(
            {"stage_collect_depth": 0, "stage_collect_busy_s": 1.0},
            stall_after_s=1.0,
        )
        assert dog.check_once(now=0.0) == []
        assert dog.check_once(now=100.0) == []  # empty: no work queued
        dog._pipeline["stage_collect_depth"] = 3
        for i in range(10):  # deep but ADVANCING: busy, not stalled
            dog._pipeline["stage_collect_busy_s"] = float(i)
            assert dog.check_once(now=110.0 + 10 * i) == []
        assert dog.trips["stage_stall"] == 0

    def test_journal_runaway_is_edge_triggered(self):
        depth = {"d": 0}
        dog = _dog(
            {"_journal": lambda: depth["d"]}, journal_runaway_depth=2
        )
        assert dog.check_once(now=0.0) == []
        depth["d"] = 3
        assert dog.check_once(now=1.0) == ["journal_runaway"]
        assert dog.check_once(now=2.0) == []  # still over: one trip
        depth["d"] = 1  # drained below the bar: re-armed
        assert dog.check_once(now=3.0) == []
        depth["d"] = 5
        assert dog.check_once(now=4.0) == ["journal_runaway"]
        assert dog.trips["journal_runaway"] == 2

    def test_scrape_dead_fires_per_newly_dead_shard(self):
        clock = FakeClock()
        shards = {
            "a:1": FakeMetricsClient(_shard_registry()),
            "b:1": FakeMetricsClient(_shard_registry()),
        }
        tel = _telemetry(shards, clock=clock)
        tel.scrape_once()
        dog = _dog({}, clock=clock, telemetry=tel)
        assert dog.check_once() == []
        shards["b:1"].fail = True
        tel.scrape_once()
        trips = dog.check_once()
        assert trips == ["scrape_dead"]
        assert dog.events[-1] == ("scrape_dead", {"endpoint": "b:1"})
        assert dog.check_once() == []  # still dead: no re-fire
        shards["b:1"].fail = False
        tel.scrape_once()  # healed...
        assert dog.check_once() == []
        shards["b:1"].fail = True
        tel.scrape_once()  # ...and dies AGAIN: a new episode
        assert dog.check_once() == ["scrape_dead"]
        assert dog.trips["scrape_dead"] == 2

    def test_trips_family_exists_zero_valued(self):
        """The khipu_watchdog_trips_total family is visible from the
        first scrape (what dashboards and the bench pin key on), all
        kinds zero until something trips."""
        dog = _dog({})
        text = dog.registry.prometheus_text()
        for kind in WATCHDOG_KINDS:
            assert (
                f'khipu_watchdog_trips_total{{kind="{kind}"}} 0'
                in text
            )

    def test_trip_emits_tracer_instant_event(self):
        tracer = Tracer()
        tracer.enable()
        dog = _dog(
            {"stage_save_depth": 2, "stage_save_busy_s": 0.0},
            tracer=tracer, stall_after_s=1.0,
        )
        dog.check_once(now=0.0)
        dog.check_once(now=1.0)
        spans = [
            s for s in tracer.snapshot()
            if s.name == "watchdog.stage_stall"
        ]
        assert len(spans) == 1
        doc = export.chrome_trace(spans=tracer.snapshot())
        evts = [
            e for e in doc["traceEvents"]
            if e.get("name") == "watchdog.stage_stall"
        ]
        assert evts and evts[0]["ph"] == "i"  # chrome instant event

    def test_phase_anomaly_trips_past_ceiling_and_rearms(self):
        """window.seal taking >30% of canonical phase wall time (with
        the off-driver seal stage, a heavy driver seal means pack work
        leaked back onto the driver) trips phase_anomaly once, stays
        quiet while it persists, and re-arms when the share
        recovers."""
        src = {"shares": {"window.seal": 0.8}, "total": 10.0}
        dog = _dog({})
        dog._phase_share_src = lambda: (src["shares"], src["total"])
        assert dog.check_once(now=0.0) == ["phase_anomaly"]
        assert dog.check_once(now=1.0) == []  # edge-triggered
        kind, tags = dog.events[-1]
        assert kind == "phase_anomaly"
        assert tags["phase"] == "window.seal"
        assert tags["share"] == 0.8 and tags["ceiling"] == 0.3
        src["shares"] = {"window.seal": 0.1}  # recovered: re-arms
        assert dog.check_once(now=2.0) == []
        src["shares"] = {"window.seal": 0.9}
        assert dog.check_once(now=3.0) == ["phase_anomaly"]
        assert dog.trips["phase_anomaly"] == 2
        # the heavy pack stage has its own, much looser ceiling
        src["shares"] = {"window.seal": 0.1, "window.pack": 0.95}
        assert dog.check_once(now=4.0) == ["phase_anomaly"]
        kind, tags = dog.events[-1]
        assert tags["phase"] == "window.pack"
        assert tags["ceiling"] == 0.85

    def test_phase_anomaly_needs_min_total_seconds(self):
        """The first milliseconds of a replay are all one phase by
        construction — shares are not judged before
        phase_share_min_total_s of canonical phase time exists."""
        src = {"total": 1.0}
        dog = _dog({}, phase_share_min_total_s=5.0)
        dog._phase_share_src = (
            lambda: ({"window.seal": 0.99}, src["total"])
        )
        assert dog.check_once(now=0.0) == []
        src["total"] = 5.0  # enough signal: judged now
        assert dog.check_once(now=1.0) == ["phase_anomaly"]

    def test_phase_anomaly_honours_configured_ceilings(self):
        dog = _dog(
            {}, phase_share_ceilings=(("window.collect", 0.5),),
        )
        dog._phase_share_src = lambda: (
            {"window.seal": 0.99, "window.collect": 0.3}, 100.0
        )
        # seal is way over the DEFAULT ceiling but only collect is
        # configured — and collect is under its bar
        assert dog.check_once(now=0.0) == []
        assert dog.trips["phase_anomaly"] == 0

    def test_clean_sweep_120_seeds_zero_trips(self):
        """Synthetic healthy-pipeline traces across 120 seeds: depths
        bounce around but busy_s ALWAYS advances while work is queued
        — the starvation signature never appears, the dog never
        barks. (The acceptance bar: a clean system is silent.)"""
        for seed in range(120):
            rng = Random(seed)
            g = {}
            busy = {s: 0.0 for s in ("collect", "persist", "save")}
            dog = _dog(g, stall_after_s=2.0)
            now = 0.0
            for _ in range(50):
                now += rng.uniform(0.5, 3.0)
                for s in busy:
                    depth = rng.randint(0, 3)
                    if depth > 0:
                        busy[s] = round(
                            busy[s] + rng.uniform(0.001, 0.5), 3
                        )
                    g[f"stage_{s}_depth"] = depth
                    g[f"stage_{s}_busy_s"] = busy[s]
                assert dog.check_once(now=now) == [], seed
            assert dog.trips == {k: 0 for k in WATCHDOG_KINDS}

    def test_background_thread_start_stop(self):
        g = {"stage_persist_depth": 1, "stage_persist_busy_s": 1.0}
        dog = Watchdog(
            config=TelemetryConfig(
                enabled=True, watchdog_interval=0.01,
                stall_after_s=0.05,
            ),
            pipeline=g, registry=MetricsRegistry(),
        )
        dog.start()
        dog.start()  # idempotent
        try:
            deadline = time.time() + 5
            while not dog.trips["stage_stall"] and time.time() < deadline:
                time.sleep(0.01)
            assert dog.trips["stage_stall"] == 1
        finally:
            dog.stop()
        assert dog._thread is None


# ------------------------------------------------------- watchdog + chaos


def _build_chain(n=8):
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG, GenesisSpec(alloc=ALLOC)
    )
    return [
        builder.add_block(
            [sign_transaction(
                Transaction(i, 10**9, 21000, ADDRS[1], 5), KEYS[0],
                chain_id=1,
            )],
            coinbase=b"\xaa" * 20,
        )
        for i in range(n)
    ]


def _pipelined_cfg():
    return dataclasses.replace(
        CFG,
        sync=SyncConfig(
            parallel_tx=False,
            commit_window_blocks=2,
            pipeline_depth=2,
            collector_join_timeout=5.0,
        ),
    )


def _reset_stage_gauges():
    # PIPELINE_GAUGES is module-global; earlier tests leave residue
    for s in ("collect", "persist", "save"):
        PIPELINE_GAUGES[f"stage_{s}_depth"] = 0
        PIPELINE_GAUGES[f"stage_{s}_busy_s"] = 0.0


class TestWatchdogChaos:
    def test_injected_persist_latency_trips_stage_stall(self):
        """A chaos latency at ``collector.persist`` holds the persist
        stage active with busy_s flat — the real watchdog thread,
        polling the REAL pipeline gauges during a pipelined replay,
        must trip ``stage_stall`` on the persist stage and land the
        instant event in the chrome trace."""
        chain = _build_chain()
        cfg = _pipelined_cfg()
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec(alloc=ALLOC))
        _reset_stage_gauges()
        tracer = Tracer()
        tracer.enable()
        dog = Watchdog(
            config=TelemetryConfig(
                enabled=True, watchdog_interval=0.01,
                stall_after_s=0.1,
            ),
            tracer=tracer, registry=MetricsRegistry(),
        )
        plan = FaultPlan(
            seed=0,
            rules=[FaultRule(
                "collector.persist", "latency", latency_s=0.6,
                times=1,
            )],
        )
        dog.start()
        try:
            with active(plan):
                ReplayDriver(bc, cfg).replay(chain)
        finally:
            dog.stop()
        assert bc.best_block_number == len(chain)  # latency, not harm
        assert dog.trips["stage_stall"] >= 1
        stages = {
            tags["stage"] for kind, tags in dog.events
            if kind == "stage_stall"
        }
        assert "persist" in stages
        doc = export.chrome_trace(spans=tracer.snapshot())
        evts = [
            e for e in doc["traceEvents"]
            if e.get("name") == "watchdog.stage_stall"
        ]
        assert evts and all(e["ph"] == "i" for e in evts)

    def test_clean_pipelined_replay_trips_nothing(self):
        """Same rig, no fault: a healthy pipeline where every stage
        finishes in well under stall_after_s keeps the dog silent."""
        chain = _build_chain()
        cfg = _pipelined_cfg()
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec(alloc=ALLOC))
        _reset_stage_gauges()
        dog = Watchdog(
            config=TelemetryConfig(
                enabled=True, watchdog_interval=0.01,
                stall_after_s=2.0,
            ),
            registry=MetricsRegistry(),
        )
        dog.start()
        try:
            ReplayDriver(bc, cfg).replay(chain)
        finally:
            dog.stop()
        assert bc.best_block_number == len(chain)
        assert dog.trips == {k: 0 for k in WATCHDOG_KINDS}


# ------------------------------------------------------- zero-cost gate


class TestZeroCostDisabled:
    def test_service_board_start_telemetry_returns_none(self, tmp_path):
        from khipu_tpu.config import DbConfig
        from khipu_tpu.service_board import ServiceBoard

        cfg = dataclasses.replace(
            fixture_config(chain_id=1),
            db=DbConfig(engine="sqlite", data_dir=str(tmp_path)),
        )
        assert cfg.telemetry.enabled is False  # the default
        board = ServiceBoard(cfg, GenesisSpec(alloc=ALLOC))
        before = {t.name for t in threading.enumerate()}
        try:
            assert board.start_telemetry() is None
            assert board.telemetry is None
            assert board._watchdog is None
            after = {t.name for t in threading.enumerate()}
            assert after == before  # no poller, no dog
            assert not any(
                t.name in ("khipu-telemetry", "khipu-watchdog")
                for t in threading.enumerate()
            )
        finally:
            board.shutdown()


# --------------------------------------------- 2-shard gRPC integration


grpc = pytest.importorskip("grpc")

from khipu_tpu.bridge import BridgeClient, BridgeServer  # noqa: E402


def _start_metric_shard(inflight):
    """A real bridge shard with its OWN registry (the PR-10
    BridgeServer seam) pre-loaded with one gauge."""
    bc = Blockchain(Storages(), CFG)
    bc.load_genesis(GenesisSpec(alloc=ALLOC))
    reg = MetricsRegistry()
    reg.gauge("khipu_pipeline_in_flight").set(inflight)
    server = BridgeServer(bc, CFG, registry=reg)
    port = server.start(port=0)
    return server, port, bc, reg


class TestTwoShardCluster:
    def test_kill_shed_heal_round_trip(self):
        """The acceptance scenario end-to-end over real gRPC: merged
        shard-labeled exposition; kill shard B → ``khipu_shard_up`` 0
        and health 0.0 within one scrape → cluster pressure 1.0 →
        writes shed with ``cluster`` blamed; restart B on the same
        port → pressure back to baseline, writes admitted again."""
        from khipu_tpu.cluster import HealthMonitor, ShardedNodeClient

        srv_a, port_a, _bc_a, _reg_a = _start_metric_shard(2)
        srv_b, port_b, bc_b, reg_b = _start_metric_shard(7)
        ep_a, ep_b = f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"
        cl = ShardedNodeClient(
            [ep_a, ep_b],
            channel_factory=lambda ep: BridgeClient(ep, deadline=2.0),
            sleep=lambda s: None,
        )
        mon = HealthMonitor(cl, down_after=1)
        tel = ClusterTelemetry(
            [ep_a, ep_b],
            config=TelemetryConfig(
                enabled=True, scrape_interval=2.0, staleness_s=6.0,
                health_threshold=0.5,
            ),
            cluster=cl,
            registry=MetricsRegistry(),
        )
        adm = AdmissionController(
            ServingConfig(), signals=[cluster_pressure(tel)],
            registry=MetricsRegistry(),
        )
        try:
            # ---- healthy baseline: federation + admission open
            assert tel.scrape_once() == 2
            lines = tel.cluster_text().splitlines()
            assert lines.count(
                "# TYPE khipu_pipeline_in_flight gauge"
            ) == 1
            assert (
                f'khipu_pipeline_in_flight{{shard="{ep_a}"}} 2'
                in lines
            )
            assert (
                f'khipu_pipeline_in_flight{{shard="{ep_b}"}} 7'
                in lines
            )
            assert mon.probe_once() == {ep_a: True, ep_b: True}
            adm.release(adm.acquire("eth_sendRawTransaction"))

            # ---- kill shard B
            srv_b.stop()
            tel.scrape_once()  # ONE scrape is the reaction bar
            assert tel.health_scores()[ep_b].score == 0.0
            assert tel.health_scores()[ep_a].score > 0.9
            assert tel.pressure() == 1.0
            rep = tel.report()
            assert rep["shards"][ep_b]["degraded"] is True
            assert rep["shards"][ep_a]["degraded"] is False
            assert mon.probe_once() == {ep_a: True, ep_b: False}
            up = dict(
                (lb["endpoint"], v)
                for name, _k, lb, v in mon._registry_samples()
                if name == "khipu_shard_up"
            )
            assert up == {ep_a: 1, ep_b: 0}
            with pytest.raises(ServerBusy, match="signal cluster"):
                adm.acquire("eth_sendRawTransaction")
            assert adm.shed_by_signal == {"cluster": 1}
            shed = adm.snapshot()["write"]["shed"]["pressure"]
            assert shed == 1
            # the dead shard ages out of the merged view; A remains
            # (scrape ages are fresh, so only families gate it here)
            in_flight = {
                lb["shard"]
                for lb, _ in tel.merged_families()[
                    "khipu_pipeline_in_flight"
                ][2]
            }
            assert ep_a in in_flight

            # ---- heal: a new server process on the SAME port
            srv_b2 = BridgeServer(bc_b, CFG, registry=reg_b)
            srv_b2.start(port=port_b)
            try:
                # the cached gRPC channel reconnects with backoff —
                # poll the scrape until the shard reads healthy
                deadline = time.time() + 15
                while (tel.health_scores()[ep_b].score <= 0.5
                       and time.time() < deadline):
                    tel.scrape_once()
                    time.sleep(0.1)
                assert tel.health_scores()[ep_b].score > 0.5
                assert tel.pressure() < 0.5
                assert mon.probe_once() == {ep_a: True, ep_b: True}
                adm.release(adm.acquire("eth_sendRawTransaction"))
                assert adm.shed_by_signal == {"cluster": 1}  # no more
            finally:
                srv_b2.stop()
        finally:
            tel.stop()
            cl.close()
            srv_a.stop()

    def test_get_metrics_rpc_round_trips_histograms(self):
        """The GetMetrics wire: a shard histogram arrives with float
        bucket bounds and renders identically on the driver side."""
        srv, port, _bc, reg = _start_metric_shard(0)
        h = reg.histogram("khipu_lat", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        client = BridgeClient(f"127.0.0.1:{port}", deadline=5.0)
        try:
            fams = client.get_metrics()
            assert fams == reg.families()
            assert fams["khipu_lat"][2][0][1]["buckets"] == {
                0.1: 1, 1.0: 2
            }
        finally:
            client.close()
            srv.stop()

    def test_eth_service_cluster_rpcs(self):
        """khipu_cluster_metrics_text / khipu_cluster_report serve the
        merged view; without telemetry attached they error cleanly."""
        from khipu_tpu.jsonrpc.eth_service import EthService, RpcError

        srv, port, _bc, _reg = _start_metric_shard(3)
        ep = f"127.0.0.1:{port}"
        tel = ClusterTelemetry(
            [ep],
            config=TelemetryConfig(
                enabled=True, scrape_interval=2.0, staleness_s=6.0
            ),
            registry=MetricsRegistry(),
        )
        bc = Blockchain(Storages(), CFG)
        bc.load_genesis(GenesisSpec(alloc=ALLOC))
        try:
            tel.scrape_once()
            svc = EthService(bc, CFG, telemetry=tel)
            text = svc.khipu_cluster_metrics_text()
            assert f'khipu_pipeline_in_flight{{shard="{ep}"}} 3' in text
            rep = svc.khipu_cluster_report()
            assert rep["shards"][ep]["up"] is True
            bare = EthService(bc, CFG)
            with pytest.raises(RpcError, match="not enabled"):
                bare.khipu_cluster_metrics_text()
            with pytest.raises(RpcError, match="not enabled"):
                bare.khipu_cluster_report()
        finally:
            tel.stop()
            srv.stop()
