"""Flight-recorder tests (khipu_tpu/observability/): zero-cost-when-
off, ring-overflow accounting, cross-thread lifecycle linkage through
the deep pipeline, occupancy agreement with the live gauge, chrome
trace_event export, the bounded fused compile cache, and the
bench --trace per-phase breakdown."""

import dataclasses
import json
import os
import sys
import threading

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import ObservabilityConfig, SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.observability import export, recorder
from khipu_tpu.observability.trace import (
    Tracer,
    _NULL_SPAN,
    span,
    tracer,
)
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.replay import ReplayDriver

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(4)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18
MINER = b"\xaa" * 20


def tx(i, nonce, to, value):
    return sign_transaction(
        Transaction(nonce, 10**9, 21_000, to, value), KEYS[i], chain_id=1
    )


def pipeline_cfg(w=2, depth=2):
    return dataclasses.replace(
        CFG,
        sync=SyncConfig(
            parallel_tx=True, commit_window_blocks=w, pipeline_depth=depth
        ),
    )


N_BLOCKS = 20


@pytest.fixture(scope="module")
def chain():
    """20 transfer blocks (windowed pipeline shape, no device needed).
    Big enough that per-window constant overhead (span record, queue
    hand-off) amortizes below the occupancy-agreement tolerance — at 5
    blocks x 3 txs the span-vs-gauge check sat on the tolerance edge
    and flaked under CI load."""
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG,
        GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}),
    )
    blocks = []
    nonces = [0] * 4
    for n in range(N_BLOCKS):
        txs = []
        for j in range(16):
            i = j % 4
            txs.append(tx(i, nonces[i], ADDRS[(i + 1) % 4], 100 + n))
            nonces[i] += 1
        blocks.append(builder.add_block(txs, coinbase=MINER))
    return blocks


def _fresh_chain(cfg):
    bc = Blockchain(Storages(), cfg)
    bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
    return bc


@pytest.fixture(scope="module")
def traced_replay(chain):
    """One pipelined replay with the recorder ON; yields
    (stats, spans snapshot). Module-scoped: several tests interrogate
    the same trace. Restores the disabled default afterwards."""
    tracer.enable()
    tracer.reset()
    try:
        cfg = pipeline_cfg(w=2, depth=2)
        bc = _fresh_chain(cfg)
        stats = ReplayDriver(bc, cfg).replay(chain)
        spans = tracer.snapshot()
        yield stats, spans
    finally:
        tracer.disable()
        tracer.reset()


# ------------------------------------------------------ disabled mode


class TestDisabledMode:
    def test_span_is_inert_singleton(self):
        assert not tracer.enabled
        s = span("anything", block=7)
        assert s is _NULL_SPAN
        assert s is span("other")  # shared: no allocation per call
        assert s.token is None
        before = tracer.recorded
        with s as inner:
            inner.set_tag("k", "v")  # all no-ops
        assert tracer.recorded == before
        assert tracer.snapshot() == []

    def test_disabled_replay_roots_bit_exact(self, chain):
        """A traced replay and an untraced replay of the same blocks
        land on byte-identical chain heads (replay validates every
        window root, so any tracing-induced divergence would raise)."""
        cfg = pipeline_cfg(w=2, depth=2)
        bc_off = _fresh_chain(cfg)
        ReplayDriver(bc_off, cfg).replay(chain)
        tracer.enable()
        tracer.reset()
        try:
            bc_on = _fresh_chain(cfg)
            ReplayDriver(bc_on, cfg).replay(chain)
        finally:
            tracer.disable()
            tracer.reset()
        h_off = bc_off.get_header_by_number(N_BLOCKS)
        h_on = bc_on.get_header_by_number(N_BLOCKS)
        assert h_off.hash == h_on.hash == chain[-1].hash
        assert h_off.state_root == h_on.state_root

    def test_config_enables_tracer(self, chain):
        """ObservabilityConfig(enabled=True) on the driver's config
        flips the process tracer on at construction."""
        cfg = dataclasses.replace(
            pipeline_cfg(),
            observability=ObservabilityConfig(
                enabled=True, ring_capacity=4096
            ),
        )
        assert not tracer.enabled
        try:
            ReplayDriver(_fresh_chain(cfg), cfg)
            assert tracer.enabled
            assert tracer.capacity == 4096
        finally:
            tracer.disable()
            tracer.reset()


# ------------------------------------------------------- ring buffer


class TestRing:
    def test_overflow_drop_oldest_and_counter(self):
        t = Tracer(capacity=8)
        t.enable()
        for i in range(20):
            t.event("e", i=i)
        assert t.recorded == 20
        assert t.dropped == 12
        kept = t.snapshot()
        assert [s.tags["i"] for s in kept] == list(range(12, 20))

    def test_reset_clears_drop_counter(self):
        t = Tracer(capacity=4)
        t.enable()
        for i in range(9):
            t.event("e", i=i)
        assert t.dropped == 5
        t.reset()
        assert t.dropped == 0 and t.snapshot() == []
        t.event("e", i=0)
        assert t.recorded == 1 and t.dropped == 0

    def test_concurrent_appends_lock_free(self):
        """8 writer threads into a small ring: no exception, exact
        recorded count, dropped = recorded - capacity."""
        t = Tracer(capacity=64)
        t.enable()

        def burst():
            for i in range(500):
                with t.span("w", i=i):
                    pass

        threads = [threading.Thread(target=burst) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert t.recorded == 4000
        assert t.dropped == 4000 - 64
        assert len(t.snapshot()) == 64


# ------------------------------------- lifecycle across the pipeline


class TestLifecycle:
    def test_cross_thread_parent_linkage(self, traced_replay):
        """window.collect / window.persist run on the collector thread
        but carry the DRIVER's seal-span token as parent — the explicit
        cross-thread edge thread-local nesting cannot express."""
        _, spans = traced_replay
        by_id = {s.sid: s for s in spans}
        collects = [s for s in spans if s.name == recorder.PHASE_COLLECT]
        assert collects, "no window.collect spans recorded"
        for c in collects:
            parent = by_id[c.parent]
            assert parent.name == recorder.PHASE_SEAL
            assert parent.tid != c.tid, "collect ran on the driver?"
            assert parent.tags["block_lo"] == c.tags["block_lo"]
        persists = [s for s in spans if s.name == recorder.PHASE_PERSIST]
        assert persists
        assert all(
            by_id[p.parent].name == recorder.PHASE_SEAL for p in persists
        )

    def test_no_nesting_violations(self, traced_replay):
        _, spans = traced_replay
        assert recorder.nesting_violations(spans) == []

    def test_trace_block_lifecycle_complete(self, traced_replay):
        """khipu_trace_block(n)'s record: every required phase present,
        in pipeline order, spanning both threads."""
        _, spans = traced_replay
        for n in (1, 3, 5):
            rec = recorder.lifecycle(spans, n)
            assert rec["complete"], rec["phaseOrder"]
            order = rec["phaseOrder"]
            assert order.index("window.build") < order.index("window.seal")
            assert (
                order.index("window.seal") < order.index("window.collect")
            )
            assert len(rec["threads"]) >= 2
        assert recorder.traced_blocks(spans) == list(range(1, N_BLOCKS + 1))

    def test_occupancy_agrees_with_gauge(self, traced_replay, chain):
        """Acceptance gate: occupancy recomputed FROM SPANS agrees with
        the live pipeline_occupancy gauge. The band allows for the
        systematic ~0.02 one-sided bias inherent to self-measurement
        (a span's clock cannot include its own record cost, the gauge's
        busy clock does); a real accounting bug diverges by tens of
        points. Scheduler preemption can still blow ANY single run's
        band on a loaded box, so disagreement re-measures on fresh
        replays — a real bug disagrees every time. (The module tracer
        stays enabled; the ring holds 64k spans, so the extra replays
        cannot overflow it for the later live-ring tests.)"""
        stats, spans = traced_replay
        if abs(recorder.occupancy(spans) - stats.pipeline_occupancy) < 0.08:
            return
        deltas = []
        for attempt in range(2):
            cfg = pipeline_cfg(w=2, depth=2)
            bc = _fresh_chain(cfg)
            already = len(tracer.snapshot())
            st = ReplayDriver(bc, cfg).replay(chain)
            sp = tracer.snapshot()[already:]  # this replay's spans only
            delta = abs(recorder.occupancy(sp) - st.pipeline_occupancy)
            if delta < 0.08:
                return
            deltas.append(delta)
        raise AssertionError(
            f"span-vs-gauge occupancy disagreed on 3/3 runs: {deltas}"
        )

    def test_phase_percentiles(self, traced_replay):
        _, spans = traced_replay
        pct = recorder.phase_percentiles(spans)
        for phase in recorder.REQUIRED_PHASES:
            assert pct[phase]["count"] > 0
            assert (
                pct[phase]["p50_s"]
                <= pct[phase]["p90_s"]
                <= pct[phase]["p99_s"]
            )


# ----------------------------------------------------------- export


class TestExport:
    def test_chrome_trace_json_valid(self, traced_replay, tmp_path):
        _, spans = traced_replay
        path = tmp_path / "trace.json"
        export.dump_chrome_trace(str(path), spans)
        doc = json.loads(path.read_text())
        events = doc["traceEvents"]
        assert events and doc["displayTimeUnit"] == "ms"
        # "C" = the counter tracks (export.counter_tracks) every dump
        # now carries — occupancy timeline + transfer-ledger bytes
        assert all(e["ph"] in ("M", "X", "i", "s", "f", "C") for e in events)
        cs = [e for e in events if e["ph"] == "C"]
        assert cs and all("ts" in e and e["args"] for e in cs)
        # every complete event carries microsecond ts + dur
        xs = [e for e in events if e["ph"] == "X"]
        assert xs and all(e["dur"] >= 0 and "ts" in e for e in xs)
        # cross-thread handoffs emit PAIRED flow events on distinct tids
        starts = {e["id"]: e for e in events if e["ph"] == "s"}
        finishes = [e for e in events if e["ph"] == "f"]
        assert finishes and starts
        for f in finishes:
            s = starts[f["id"]]
            assert s["tid"] != f["tid"]

    def test_snapshot_rpc_payload(self, traced_replay):
        """The khipu_traces RPC body while the ring still holds the
        replay's spans (module fixture keeps the tracer enabled)."""
        snap = export.snapshot()
        assert snap["enabled"] and snap["dropped"] == 0
        assert snap["blocks"] == list(range(1, N_BLOCKS + 1))
        assert set(recorder.REQUIRED_PHASES) <= set(
            snap["phasePercentiles"]
        )
        assert 0.0 <= snap["occupancy"] <= 1.0
        assert {"hits", "misses", "evictions"} <= set(
            snap["compileCache"]
        )
        block = export.trace_block(2)
        assert block["complete"]

    def test_eth_service_exposes_trace_rpcs(self):
        from khipu_tpu.jsonrpc.eth_service import EthService

        for name in ("khipu_traces", "khipu_trace_block",
                     "khipu_dump_chrome_trace", "khipu_metrics",
                     "khipu_metrics_text"):
            assert callable(getattr(EthService, name))


# ------------------------------------------------- fused compile cache


class TestCompileCache:
    def test_lru_eviction_bounded_and_logged(self):
        from khipu_tpu.trie.fused import _build_fused, compile_cache

        old_cap = compile_cache.stats()["capacity"]
        compile_cache.clear()
        recorder.compile_log.reset()
        try:
            compile_cache.set_capacity(2)
            sigs = [((1, 16, 4),), ((1, 32, 4),), ((1, 48, 4),)]
            for sig in sigs:
                _build_fused(sig, 8, True, 0)
            st = compile_cache.stats()
            assert st["size"] == 2 and st["capacity"] == 2
            log = recorder.compile_log.snapshot()
            assert log["misses"] == 3
            assert log["evictions"] == 1  # oldest signature evicted
            # the evicted signature misses again; the resident ones hit
            _build_fused(sigs[0], 8, True, 0)
            _build_fused(sigs[2], 8, True, 0)
            log = recorder.compile_log.snapshot()
            assert log["misses"] == 4 and log["hits"] == 1
            kinds = [e["kind"] for e in log["events"]]
            assert kinds.count("evict") == log["evictions"]
        finally:
            compile_cache.set_capacity(old_cap)
            compile_cache.clear()
            recorder.compile_log.reset()

    def test_set_capacity_evicts_down(self):
        from khipu_tpu.trie.fused import _build_fused, compile_cache

        old_cap = compile_cache.stats()["capacity"]
        compile_cache.clear()
        recorder.compile_log.reset()
        try:
            compile_cache.set_capacity(8)
            for n in (16, 32, 48, 64):
                _build_fused(((1, n, 4),), 8, True, 0)
            assert compile_cache.stats()["size"] == 4
            compile_cache.set_capacity(1)
            assert compile_cache.stats()["size"] == 1
            assert recorder.compile_log.snapshot()["evictions"] == 3
        finally:
            compile_cache.set_capacity(old_cap)
            compile_cache.clear()
            recorder.compile_log.reset()


# ------------------------------------------------- bench.py --trace


class TestBenchTrace:
    def test_traced_bench_breakdown_matches_wall(self):
        """Satellite gate: the --trace per-phase breakdown (driver
        phases tile the driver's wall clock) sums to within 10% of the
        replay's measured wall time on the tiny fixture chain. Host
        hasher (device_commit=False) keeps this out of 'slow'."""
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if root not in sys.path:
            sys.path.insert(0, root)
        from bench import run_traced_replay

        # The timing-agreement checks retry over up to 3 independent
        # runs: on a loaded CI box the scheduler can preempt the
        # process between a span exit and the busy-clock stop, pushing
        # any SINGLE run past the band — while a real accounting bug
        # disagrees on every run. The structural checks (phases
        # present, no drops, block count) assert unconditionally.
        for attempt in range(3):
            stats, report = run_traced_replay(
                n_blocks=24, txs_per_block=8, window=2,
                pipeline_depth=2, device_commit=False,
            )
            assert not tracer.enabled  # helper restores the default
            assert stats.blocks == 24
            assert report["wall_s"] > 0
            for phase in recorder.REQUIRED_PHASES:
                assert phase in report["phase_seconds"], (
                    report["phase_seconds"]
                )
            assert report["dropped"] == 0
            wall_ok = (
                abs(report["driver_total_s"] - report["wall_s"])
                <= 0.10 * report["wall_s"]
            )
            # same self-measurement bias allowance as
            # test_occupancy_agrees_with_gauge
            occ_ok = abs(
                report["occupancy_spans"] - report["occupancy_gauge"]
            ) < 0.08
            if wall_ok and occ_ok:
                break
        else:
            raise AssertionError(
                "breakdown disagreed with wall clock on 3/3 runs: "
                f"{report}"
            )


# ------------------------------------------------- unified registry


class TestRegistry:
    """khipu_tpu/observability/registry.py: the typed instrument set +
    pull collectors every legacy counter dict migrated onto."""

    def test_counter_gauge_histogram(self):
        from khipu_tpu.observability.registry import MetricsRegistry

        r = MetricsRegistry()
        c = r.counter("reqs_total", help="requests")
        c.inc()
        c.inc(4)
        assert c.value == 5
        g = r.gauge("depth")
        g.set(7)
        g.inc()
        g.dec(3)
        assert g.value == 5
        h = r.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.05, 5.0):
            h.observe(v)
        hv = h.value
        assert hv["count"] == 4
        assert abs(hv["sum"] - 5.105) < 1e-9
        # cumulative le semantics: 1 <=0.01, 3 <=0.1, 3 <=1.0 (+Inf=4)
        assert hv["buckets"] == {0.01: 1, 0.1: 3, 1.0: 3}

    def test_idempotent_reregister_and_kind_conflict(self):
        from khipu_tpu.observability.registry import MetricsRegistry

        r = MetricsRegistry()
        a = r.counter("x_total")
        assert r.counter("x_total") is a  # same (name, labels) -> same
        with pytest.raises(ValueError):
            r.gauge("x_total")  # kind flip is a bug, loudly
        # distinct labels are distinct instruments of one family
        ep1 = r.counter("y_total", labels={"endpoint": "a"})
        ep2 = r.counter("y_total", labels={"endpoint": "b"})
        assert ep1 is not ep2
        ep1.inc(2)
        snap = r.snapshot()
        assert snap["y_total"] == {'endpoint="a"': 2, 'endpoint="b"': 0}

    def test_gauge_group_shim_keeps_dict_call_sites(self):
        from khipu_tpu.observability.registry import MetricsRegistry

        r = MetricsRegistry()
        gg = r.gauge_group("khipu_pipe", {"in_flight": 0, "depth": 2})
        # the verbatim legacy write patterns
        gg["in_flight"] += 1
        gg["in_flight"] += 1
        gg["depth"] = 4
        assert gg["in_flight"] == 2
        assert "depth" in gg and len(gg) == 2
        assert dict(gg.items())["depth"] == 4
        # the values LIVE in the registry, served by name
        snap = r.snapshot()
        assert snap["khipu_pipe_in_flight"] == 2
        assert snap["khipu_pipe_depth"] == 4
        gg.reset()
        assert r.snapshot()["khipu_pipe_depth"] == 2

    def test_collector_replace_by_key_and_failure_dropped(self):
        from khipu_tpu.observability.registry import MetricsRegistry

        r = MetricsRegistry()
        r.register_collector(
            "j", lambda: [("d", "gauge", {}, 1)]
        )
        r.register_collector(
            "j", lambda: [("d", "gauge", {}, 9)]
        )  # newest owner of the state wins — no dead-entry leak
        def boom():
            raise RuntimeError("broken source")
        r.register_collector("bad", boom)
        snap = r.snapshot()
        assert snap["d"] == 9  # replaced, not duplicated
        assert "bad" not in snap  # failure dropped, scrape survived
        r.unregister_collector("j")
        assert "d" not in r.snapshot()

    def test_prometheus_text_exposition(self):
        from khipu_tpu.observability.registry import MetricsRegistry

        r = MetricsRegistry()
        r.counter("c_total", help="a counter").inc(3)
        r.gauge("g", labels={"shard": "a"}).set(1)
        r.gauge("g", labels={"shard": "b"}).set(2)
        h = r.histogram("h_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        text = r.prometheus_text()
        lines = text.splitlines()
        assert "# HELP c_total a counter" in lines
        assert "# TYPE c_total counter" in lines
        assert "c_total 3" in lines
        assert 'g{shard="a"} 1' in lines and 'g{shard="b"} 2' in lines
        assert lines.count("# TYPE g gauge") == 1  # ONE family header
        assert 'h_seconds_bucket{le="0.1"} 1' in lines
        assert 'h_seconds_bucket{le="1.0"} 2' in lines
        assert 'h_seconds_bucket{le="+Inf"} 2' in lines
        assert "h_seconds_count 2" in lines
        assert any(ln.startswith("h_seconds_sum ") for ln in lines)

    def test_exposition_escaping_hostile_values_round_trip(self):
        """Exposition-format escaping audit (the PR-10 satellite):
        backslash, double-quote, and newline in label VALUES and
        backslash/newline in HELP text must round-trip per format
        0.0.4 — a label value containing a literal ``\\n`` used to be
        able to smuggle a fake sample line into the document."""
        from khipu_tpu.observability.registry import MetricsRegistry

        hostile = 'a\\b"c\nd'
        r = MetricsRegistry()
        r.gauge("g", labels={"ep": hostile}).set(1)
        r.counter(
            "c_total", help='back\\slash and\nnewline "quoted"'
        ).inc(2)
        text = r.prometheus_text()
        lines = text.splitlines()
        # label value: \ -> \\, " -> \", newline -> \n (no raw newline
        # survives inside a sample line)
        assert 'g{ep="a\\\\b\\"c\\nd"} 1' in lines, lines
        # HELP: \ -> \\, newline -> \n, quotes stay verbatim
        assert (
            '# HELP c_total back\\\\slash and\\nnewline "quoted"'
            in lines
        ), lines
        # nothing hostile injected a bogus line: every line is a
        # comment or starts with a known family name
        for ln in lines:
            assert ln.startswith(("#", "g{", "c_total")), ln
        # and the escapes DECODE back to the original strings under
        # the format's unescape rules (round-trip, not just mangling)
        sample = next(ln for ln in lines if ln.startswith("g{"))
        raw = sample[len('g{ep="'):sample.rindex('"')]
        unescaped = (
            raw.replace("\\\\", "\x00")
            .replace('\\"', '"')
            .replace("\\n", "\n")
            .replace("\x00", "\\")
        )
        assert unescaped == hostile

    def test_process_registry_serves_migrated_families(self):
        """The legacy dicts (PIPELINE_GAUGES, WINDOW_GAUGES, chaos
        fault log, tracer ring health) all surface as families of THE
        process registry."""
        from khipu_tpu.observability.registry import REGISTRY
        import khipu_tpu.chaos.plan  # noqa: F401 - registers collector
        import khipu_tpu.ledger.window  # noqa: F401
        import khipu_tpu.sync.replay  # noqa: F401

        snap = REGISTRY.snapshot()
        for family in (
            "khipu_pipeline_depth",
            "khipu_pipeline_in_flight",
            "khipu_pipeline_windows_sealed",
            "khipu_window_fused_fallbacks",
            "khipu_chaos_faults_fired_total",
            "khipu_trace_spans_recorded_total",
            "khipu_trace_enabled",
        ):
            assert family in snap, family


# --------------------------------------------- snapshot fence (bugfix)


class TestSnapshotFence:
    def test_two_thread_snapshot_stress(self):
        """The copy-consistency fix: a reader snapshotting while a
        writer floods the ring must never raise (deque mutation mid-
        iteration) and every snapshot must be internally ordered —
        oldest first, tags monotonic — even across drop-oldest
        overflow."""
        t = Tracer(capacity=256)
        t.enable()
        stop = threading.Event()
        writer_err = []

        def writer():
            i = 0
            try:
                while not stop.is_set():
                    t.event("stress", i=i)
                    i += 1
            except Exception as e:  # pragma: no cover - the regression
                writer_err.append(e)

        th = threading.Thread(target=writer, name="stress-writer")
        th.start()
        try:
            snapshots = 0
            for _ in range(400):
                snap = t.snapshot()
                assert len(snap) <= t.capacity
                seq = [s.tags["i"] for s in snap if s.name == "stress"]
                # a torn copy would interleave out of order or dup
                assert seq == sorted(seq)
                assert len(set(seq)) == len(seq)
                snapshots += 1
        finally:
            stop.set()
            th.join(timeout=10)
        assert not writer_err
        assert snapshots == 400
        assert t.dropped > 0  # the stress actually wrapped the ring


# ----------------------------------- metrics superset + text agreement


class TestMetricsSuperset:
    @pytest.fixture(scope="class")
    def svc(self, chain):
        """EthService over a freshly replayed pipelined chain."""
        from khipu_tpu.jsonrpc.eth_service import EthService
        from khipu_tpu.txpool import PendingTransactionsPool

        cfg = pipeline_cfg(w=2, depth=2)
        bc = _fresh_chain(cfg)
        ReplayDriver(bc, cfg).replay(chain)
        return EthService(bc, cfg, PendingTransactionsPool())

    def test_khipu_metrics_is_key_compatible_superset(self, svc):
        """Every pre-registry key survives unchanged; the registry
        snapshot rides along as a new section and AGREES with the
        legacy values it mirrors."""
        out = svc.khipu_metrics()
        # legacy surface, verbatim
        assert out["bestBlockNumber"] == N_BLOCKS
        assert {"account", "storage", "evmcode"} <= set(out["stores"])
        for legacy in ("cacheHitRate", "cacheReadCount"):
            assert legacy in out["stores"]["account"]
        assert {
            "depth", "inFlight", "windowsSealed", "windowsCollected",
            "occupancy", "driverStallSeconds", "collectorBusySeconds",
            "collectorDeaths", "syncFallbackWindows",
        } <= set(out["pipeline"])
        assert {"fusedFallbacks", "journalDepth", "faults"} <= set(
            out["robustness"]
        )
        # the superset sections
        reg = out["registry"]
        assert reg["khipu_pipeline_windows_sealed"] == (
            out["pipeline"]["windowsSealed"]
        )
        assert reg["khipu_pipeline_depth"] == out["pipeline"]["depth"]
        assert reg["khipu_window_fused_fallbacks"] == (
            out["robustness"]["fusedFallbacks"]
        )
        assert reg["khipu_best_block_number"] == N_BLOCKS
        assert "phaseLatency" in out
        json.dumps(out)  # the whole document stays JSON-serializable

    def test_metrics_text_agrees_with_snapshot(self, svc):
        """khipu_metrics_text serves the SAME values the structured
        snapshot carries — one source of truth, two encodings."""
        out = svc.khipu_metrics()
        text = svc.khipu_metrics_text()
        lines = text.splitlines()
        assert f"khipu_best_block_number {N_BLOCKS}" in lines
        sealed = out["pipeline"]["windowsSealed"]
        assert f"khipu_pipeline_windows_sealed {sealed}" in lines
        pending = out["pendingTxs"]
        assert f"khipu_pending_txs {pending}" in lines


# --------------------------------------- bench --trace registry smoke


class TestBenchTraceRegistrySmoke:
    def test_trace_smoke_chrome_valid_and_families_unique(self, tmp_path):
        """CI satellite: the bench --trace path end to end — the chrome
        trace it writes is valid JSON with events, and EVERY family in
        the registry snapshot appears exactly once (one # TYPE line,
        >=1 sample line) in the khipu_metrics_text exposition."""
        import re

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if root not in sys.path:
            sys.path.insert(0, root)
        from bench import run_traced_replay

        from khipu_tpu.observability.registry import REGISTRY

        chrome = tmp_path / "bench_trace.json"
        stats, report = run_traced_replay(
            n_blocks=12, txs_per_block=4, window=2, pipeline_depth=2,
            device_commit=False, chrome_out=str(chrome),
        )
        assert stats.blocks == 12
        assert report["chrome_trace"] == str(chrome)
        doc = json.loads(chrome.read_text())
        assert doc["traceEvents"]
        assert report["registry_families"] > 0
        # phase histograms observed real latencies during the run
        assert report["phase_observations"]
        assert sum(report["phase_observations"].values()) > 0
        # the device-resident-commit pin: collect-phase d2h stays at
        # (at most) the 32 B/block rootcheck — the staged pipeline must
        # never pull node bytes back to host on the critical path. The
        # host-hasher smoke run moves ZERO device bytes in collect; the
        # device path is pinned <=256 B/block by TestDeviceMirrorCommit.
        assert report["movement"]["collect_d2h_bytes_per_block"] <= 64, (
            report["movement"]
        )

        snap = REGISTRY.snapshot()
        text = REGISTRY.prometheus_text()
        lines = text.splitlines()
        type_lines = [ln for ln in lines if ln.startswith("# TYPE ")]
        # families and TYPE headers are in bijection
        assert len(type_lines) == len(snap)
        for name in snap:
            headers = [
                ln for ln in type_lines
                if ln.startswith(f"# TYPE {name} ")
            ]
            assert len(headers) == 1, name
            pat = re.compile(
                rf"^{re.escape(name)}(_bucket|_sum|_count)?(\{{| )"
            )
            assert any(
                pat.match(ln) for ln in lines if not ln.startswith("#")
            ), name
