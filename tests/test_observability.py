"""Flight-recorder tests (khipu_tpu/observability/): zero-cost-when-
off, ring-overflow accounting, cross-thread lifecycle linkage through
the deep pipeline, occupancy agreement with the live gauge, chrome
trace_event export, the bounded fused compile cache, and the
bench --trace per-phase breakdown."""

import dataclasses
import json
import os
import sys
import threading

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import ObservabilityConfig, SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.observability import export, recorder
from khipu_tpu.observability.trace import (
    Tracer,
    _NULL_SPAN,
    span,
    tracer,
)
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.replay import ReplayDriver

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(4)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18
MINER = b"\xaa" * 20


def tx(i, nonce, to, value):
    return sign_transaction(
        Transaction(nonce, 10**9, 21_000, to, value), KEYS[i], chain_id=1
    )


def pipeline_cfg(w=2, depth=2):
    return dataclasses.replace(
        CFG,
        sync=SyncConfig(
            parallel_tx=True, commit_window_blocks=w, pipeline_depth=depth
        ),
    )


N_BLOCKS = 20


@pytest.fixture(scope="module")
def chain():
    """20 transfer blocks (windowed pipeline shape, no device needed).
    Big enough that per-window constant overhead (span record, queue
    hand-off) amortizes below the occupancy-agreement tolerance — at 5
    blocks x 3 txs the span-vs-gauge check sat on the tolerance edge
    and flaked under CI load."""
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG,
        GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}),
    )
    blocks = []
    nonces = [0] * 4
    for n in range(N_BLOCKS):
        txs = []
        for j in range(16):
            i = j % 4
            txs.append(tx(i, nonces[i], ADDRS[(i + 1) % 4], 100 + n))
            nonces[i] += 1
        blocks.append(builder.add_block(txs, coinbase=MINER))
    return blocks


def _fresh_chain(cfg):
    bc = Blockchain(Storages(), cfg)
    bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
    return bc


@pytest.fixture(scope="module")
def traced_replay(chain):
    """One pipelined replay with the recorder ON; yields
    (stats, spans snapshot). Module-scoped: several tests interrogate
    the same trace. Restores the disabled default afterwards."""
    tracer.enable()
    tracer.reset()
    try:
        cfg = pipeline_cfg(w=2, depth=2)
        bc = _fresh_chain(cfg)
        stats = ReplayDriver(bc, cfg).replay(chain)
        spans = tracer.snapshot()
        yield stats, spans
    finally:
        tracer.disable()
        tracer.reset()


# ------------------------------------------------------ disabled mode


class TestDisabledMode:
    def test_span_is_inert_singleton(self):
        assert not tracer.enabled
        s = span("anything", block=7)
        assert s is _NULL_SPAN
        assert s is span("other")  # shared: no allocation per call
        assert s.token is None
        before = tracer.recorded
        with s as inner:
            inner.set_tag("k", "v")  # all no-ops
        assert tracer.recorded == before
        assert tracer.snapshot() == []

    def test_disabled_replay_roots_bit_exact(self, chain):
        """A traced replay and an untraced replay of the same blocks
        land on byte-identical chain heads (replay validates every
        window root, so any tracing-induced divergence would raise)."""
        cfg = pipeline_cfg(w=2, depth=2)
        bc_off = _fresh_chain(cfg)
        ReplayDriver(bc_off, cfg).replay(chain)
        tracer.enable()
        tracer.reset()
        try:
            bc_on = _fresh_chain(cfg)
            ReplayDriver(bc_on, cfg).replay(chain)
        finally:
            tracer.disable()
            tracer.reset()
        h_off = bc_off.get_header_by_number(N_BLOCKS)
        h_on = bc_on.get_header_by_number(N_BLOCKS)
        assert h_off.hash == h_on.hash == chain[-1].hash
        assert h_off.state_root == h_on.state_root

    def test_config_enables_tracer(self, chain):
        """ObservabilityConfig(enabled=True) on the driver's config
        flips the process tracer on at construction."""
        cfg = dataclasses.replace(
            pipeline_cfg(),
            observability=ObservabilityConfig(
                enabled=True, ring_capacity=4096
            ),
        )
        assert not tracer.enabled
        try:
            ReplayDriver(_fresh_chain(cfg), cfg)
            assert tracer.enabled
            assert tracer.capacity == 4096
        finally:
            tracer.disable()
            tracer.reset()


# ------------------------------------------------------- ring buffer


class TestRing:
    def test_overflow_drop_oldest_and_counter(self):
        t = Tracer(capacity=8)
        t.enable()
        for i in range(20):
            t.event("e", i=i)
        assert t.recorded == 20
        assert t.dropped == 12
        kept = t.snapshot()
        assert [s.tags["i"] for s in kept] == list(range(12, 20))

    def test_reset_clears_drop_counter(self):
        t = Tracer(capacity=4)
        t.enable()
        for i in range(9):
            t.event("e", i=i)
        assert t.dropped == 5
        t.reset()
        assert t.dropped == 0 and t.snapshot() == []
        t.event("e", i=0)
        assert t.recorded == 1 and t.dropped == 0

    def test_concurrent_appends_lock_free(self):
        """8 writer threads into a small ring: no exception, exact
        recorded count, dropped = recorded - capacity."""
        t = Tracer(capacity=64)
        t.enable()

        def burst():
            for i in range(500):
                with t.span("w", i=i):
                    pass

        threads = [threading.Thread(target=burst) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert t.recorded == 4000
        assert t.dropped == 4000 - 64
        assert len(t.snapshot()) == 64


# ------------------------------------- lifecycle across the pipeline


class TestLifecycle:
    def test_cross_thread_parent_linkage(self, traced_replay):
        """window.collect / window.persist run on the collector thread
        but carry the DRIVER's seal-span token as parent — the explicit
        cross-thread edge thread-local nesting cannot express."""
        _, spans = traced_replay
        by_id = {s.sid: s for s in spans}
        collects = [s for s in spans if s.name == recorder.PHASE_COLLECT]
        assert collects, "no window.collect spans recorded"
        for c in collects:
            parent = by_id[c.parent]
            assert parent.name == recorder.PHASE_SEAL
            assert parent.tid != c.tid, "collect ran on the driver?"
            assert parent.tags["block_lo"] == c.tags["block_lo"]
        persists = [s for s in spans if s.name == recorder.PHASE_PERSIST]
        assert persists
        assert all(
            by_id[p.parent].name == recorder.PHASE_SEAL for p in persists
        )

    def test_no_nesting_violations(self, traced_replay):
        _, spans = traced_replay
        assert recorder.nesting_violations(spans) == []

    def test_trace_block_lifecycle_complete(self, traced_replay):
        """khipu_trace_block(n)'s record: every required phase present,
        in pipeline order, spanning both threads."""
        _, spans = traced_replay
        for n in (1, 3, 5):
            rec = recorder.lifecycle(spans, n)
            assert rec["complete"], rec["phaseOrder"]
            order = rec["phaseOrder"]
            assert order.index("window.build") < order.index("window.seal")
            assert (
                order.index("window.seal") < order.index("window.collect")
            )
            assert len(rec["threads"]) >= 2
        assert recorder.traced_blocks(spans) == list(range(1, N_BLOCKS + 1))

    def test_occupancy_agrees_with_gauge(self, traced_replay, chain):
        """Acceptance gate: occupancy recomputed FROM SPANS agrees with
        the live pipeline_occupancy gauge. The band allows for the
        systematic ~0.02 one-sided bias inherent to self-measurement
        (a span's clock cannot include its own record cost, the gauge's
        busy clock does); a real accounting bug diverges by tens of
        points. Scheduler preemption can still blow ANY single run's
        band on a loaded box, so disagreement re-measures on fresh
        replays — a real bug disagrees every time. (The module tracer
        stays enabled; the ring holds 64k spans, so the extra replays
        cannot overflow it for the later live-ring tests.)"""
        stats, spans = traced_replay
        if abs(recorder.occupancy(spans) - stats.pipeline_occupancy) < 0.08:
            return
        deltas = []
        for attempt in range(2):
            cfg = pipeline_cfg(w=2, depth=2)
            bc = _fresh_chain(cfg)
            already = len(tracer.snapshot())
            st = ReplayDriver(bc, cfg).replay(chain)
            sp = tracer.snapshot()[already:]  # this replay's spans only
            delta = abs(recorder.occupancy(sp) - st.pipeline_occupancy)
            if delta < 0.08:
                return
            deltas.append(delta)
        raise AssertionError(
            f"span-vs-gauge occupancy disagreed on 3/3 runs: {deltas}"
        )

    def test_phase_percentiles(self, traced_replay):
        _, spans = traced_replay
        pct = recorder.phase_percentiles(spans)
        for phase in recorder.REQUIRED_PHASES:
            assert pct[phase]["count"] > 0
            assert (
                pct[phase]["p50_s"]
                <= pct[phase]["p90_s"]
                <= pct[phase]["p99_s"]
            )


# ----------------------------------------------------------- export


class TestExport:
    def test_chrome_trace_json_valid(self, traced_replay, tmp_path):
        _, spans = traced_replay
        path = tmp_path / "trace.json"
        export.dump_chrome_trace(str(path), spans)
        doc = json.loads(path.read_text())
        events = doc["traceEvents"]
        assert events and doc["displayTimeUnit"] == "ms"
        assert all(e["ph"] in ("M", "X", "i", "s", "f") for e in events)
        # every complete event carries microsecond ts + dur
        xs = [e for e in events if e["ph"] == "X"]
        assert xs and all(e["dur"] >= 0 and "ts" in e for e in xs)
        # cross-thread handoffs emit PAIRED flow events on distinct tids
        starts = {e["id"]: e for e in events if e["ph"] == "s"}
        finishes = [e for e in events if e["ph"] == "f"]
        assert finishes and starts
        for f in finishes:
            s = starts[f["id"]]
            assert s["tid"] != f["tid"]

    def test_snapshot_rpc_payload(self, traced_replay):
        """The khipu_traces RPC body while the ring still holds the
        replay's spans (module fixture keeps the tracer enabled)."""
        snap = export.snapshot()
        assert snap["enabled"] and snap["dropped"] == 0
        assert snap["blocks"] == list(range(1, N_BLOCKS + 1))
        assert set(recorder.REQUIRED_PHASES) <= set(
            snap["phasePercentiles"]
        )
        assert 0.0 <= snap["occupancy"] <= 1.0
        assert {"hits", "misses", "evictions"} <= set(
            snap["compileCache"]
        )
        block = export.trace_block(2)
        assert block["complete"]

    def test_eth_service_exposes_trace_rpcs(self):
        from khipu_tpu.jsonrpc.eth_service import EthService

        for name in ("khipu_traces", "khipu_trace_block",
                     "khipu_dump_chrome_trace"):
            assert callable(getattr(EthService, name))


# ------------------------------------------------- fused compile cache


class TestCompileCache:
    def test_lru_eviction_bounded_and_logged(self):
        from khipu_tpu.trie.fused import _build_fused, compile_cache

        old_cap = compile_cache.stats()["capacity"]
        compile_cache.clear()
        recorder.compile_log.reset()
        try:
            compile_cache.set_capacity(2)
            sigs = [((1, 16, 4),), ((1, 32, 4),), ((1, 48, 4),)]
            for sig in sigs:
                _build_fused(sig, 8, True, 0)
            st = compile_cache.stats()
            assert st["size"] == 2 and st["capacity"] == 2
            log = recorder.compile_log.snapshot()
            assert log["misses"] == 3
            assert log["evictions"] == 1  # oldest signature evicted
            # the evicted signature misses again; the resident ones hit
            _build_fused(sigs[0], 8, True, 0)
            _build_fused(sigs[2], 8, True, 0)
            log = recorder.compile_log.snapshot()
            assert log["misses"] == 4 and log["hits"] == 1
            kinds = [e["kind"] for e in log["events"]]
            assert kinds.count("evict") == log["evictions"]
        finally:
            compile_cache.set_capacity(old_cap)
            compile_cache.clear()
            recorder.compile_log.reset()

    def test_set_capacity_evicts_down(self):
        from khipu_tpu.trie.fused import _build_fused, compile_cache

        old_cap = compile_cache.stats()["capacity"]
        compile_cache.clear()
        recorder.compile_log.reset()
        try:
            compile_cache.set_capacity(8)
            for n in (16, 32, 48, 64):
                _build_fused(((1, n, 4),), 8, True, 0)
            assert compile_cache.stats()["size"] == 4
            compile_cache.set_capacity(1)
            assert compile_cache.stats()["size"] == 1
            assert recorder.compile_log.snapshot()["evictions"] == 3
        finally:
            compile_cache.set_capacity(old_cap)
            compile_cache.clear()
            recorder.compile_log.reset()


# ------------------------------------------------- bench.py --trace


class TestBenchTrace:
    def test_traced_bench_breakdown_matches_wall(self):
        """Satellite gate: the --trace per-phase breakdown (driver
        phases tile the driver's wall clock) sums to within 10% of the
        replay's measured wall time on the tiny fixture chain. Host
        hasher (device_commit=False) keeps this out of 'slow'."""
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if root not in sys.path:
            sys.path.insert(0, root)
        from bench import run_traced_replay

        # The timing-agreement checks retry over up to 3 independent
        # runs: on a loaded CI box the scheduler can preempt the
        # process between a span exit and the busy-clock stop, pushing
        # any SINGLE run past the band — while a real accounting bug
        # disagrees on every run. The structural checks (phases
        # present, no drops, block count) assert unconditionally.
        for attempt in range(3):
            stats, report = run_traced_replay(
                n_blocks=24, txs_per_block=8, window=2,
                pipeline_depth=2, device_commit=False,
            )
            assert not tracer.enabled  # helper restores the default
            assert stats.blocks == 24
            assert report["wall_s"] > 0
            for phase in recorder.REQUIRED_PHASES:
                assert phase in report["phase_seconds"], (
                    report["phase_seconds"]
                )
            assert report["dropped"] == 0
            wall_ok = (
                abs(report["driver_total_s"] - report["wall_s"])
                <= 0.10 * report["wall_s"]
            )
            # same self-measurement bias allowance as
            # test_occupancy_agrees_with_gauge
            occ_ok = abs(
                report["occupancy_spans"] - report["occupancy_gauge"]
            ) < 0.08
            if wall_ok and occ_ok:
                break
        else:
            raise AssertionError(
                "breakdown disagreed with wall clock on 3/3 runs: "
                f"{report}"
            )
