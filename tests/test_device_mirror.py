"""Device-resident word-major node mirror (storage/device_mirror.py):
admit -> verify round trip, corruption detection, ring eviction, and
read-back. Runs on the CPU backend via the jnp sponge (same digests)."""

import random

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.storage.device_mirror import DeviceNodeMirror


@pytest.fixture(scope="module")
def mirror():
    m = DeviceNodeMirror(capacity_rows_per_class=1024)
    rng = random.Random(5)
    items = {}
    for _ in range(40):
        enc = rng.randbytes(rng.choice([70, 130, 300, 532]))
        items[keccak256(enc)] = enc
    m.admit(items)
    m.flush()
    return m, items


def test_verify_clean(mirror):
    m, items = mirror
    assert m.resident_count == len(items)
    assert m.verify() == 0


def test_read_back(mirror):
    m, items = mirror
    for h, enc in list(items.items())[:5]:
        assert m.contains(h)
        assert m.get(h) == enc
    assert m.get(b"\x00" * 32) is None


def test_corrupt_admit_detected():
    m = DeviceNodeMirror(capacity_rows_per_class=1024)
    enc = b"\xab" * 64
    m.admit({keccak256(enc): enc, b"\x99" * 32: b"\xcd" * 64})
    m.flush()
    assert m.verify() == 1  # exactly the forged claim fails


def test_ring_eviction():
    m = DeviceNodeMirror(capacity_rows_per_class=1024)
    items = {}
    for i in range(1500):
        enc = i.to_bytes(8, "big") * 9
        items[keccak256(enc)] = enc
    m.admit(items)
    m.flush()
    assert m.resident_count <= 1024
    assert m.verify() == 0  # evicted rows dropped, survivors intact


def test_exact_length_class():
    """Uniform-length populations store unpadded (in-kernel pad):
    verify and read-back must behave identically to the generic class."""
    import numpy as np

    rng = random.Random(11)
    m2 = DeviceNodeMirror(capacity_rows_per_class=1024)
    raw_full = np.frombuffer(
        rng.randbytes(64 * 1024), dtype=np.uint8
    ).reshape(1024, 64)
    hs = [keccak256(raw_full[i].tobytes()) for i in range(1024)]
    m2.admit_packed(hs, raw_full, [64] * 1024, exact=True)
    assert m2.verify() == 0
    assert m2.get(hs[0]) == raw_full[0].tobytes()
    assert m2.resident_count == 1024


def test_duplicate_admit_bookkeeping():
    """Re-admitting a resident hash must not inflate resident_count,
    and ring eviction of the OLD copy must not unmap the newer row."""
    m = DeviceNodeMirror(capacity_rows_per_class=1024)
    enc = b"\x77" * 64
    h = keccak256(enc)
    m.admit({h: enc})
    m.flush()
    assert m.resident_count == 1
    # duplicate admit via a fresh staging round (new tile, same hash)
    m.admit({h: enc})
    m.flush()
    assert m.resident_count == 1
    assert m.get(h) == enc
    assert m.verify() == 0


def _device_tile(encs):
    """One padded TILE of on-device encodings + claims: the first
    len(encs) rows are real, the rest repeat row 0 (claim-consistent
    padding, the unit-test analog of the fused dummy row)."""
    import jax.numpy as jnp
    import numpy as np

    from khipu_tpu.storage.device_mirror import RATE, TILE

    width = RATE
    padded = np.zeros((TILE, width), np.uint8)
    claims = np.zeros((TILE, 32), np.uint8)
    for r in range(TILE):
        enc = encs[r] if r < len(encs) else encs[0]
        padded[r, : len(enc)] = np.frombuffer(enc, np.uint8)
        padded[r, len(enc)] ^= 0x01
        padded[r, width - 1] ^= 0x80
        claims[r] = np.frombuffer(keccak256(enc), np.uint8)
    return jnp.asarray(padded), jnp.asarray(claims)


def test_alias_rows_hidden_until_rekey():
    """Device-admitted window rows live in the placeholder (alias)
    namespace: invisible to content-address reads until the persist
    stage's rekey publishes them under their real hashes — a reader
    following a published root must never see un-published rows."""
    from khipu_tpu.storage.device_mirror import TILE

    m = DeviceNodeMirror(capacity_rows_per_class=1024)
    encs = [bytes([i + 1]) * (40 + 7 * i) for i in range(3)]
    enc_dev, claim_dev = _device_tile(encs)
    aliases = [b"\xaa" + i.to_bytes(31, "big") for i in range(3)]
    keys = aliases + [None] * (TILE - 3)
    lengths = [len(e) for e in encs] + [0] * (TILE - 3)
    m.admit_device(1, keys, enc_dev, claim_dev, lengths)
    for enc in encs:
        assert m.get(keccak256(enc)) is None, "unpublished row served"
    assert m.verify() == 0  # claim-consistent even while aliased
    mapping = {a: keccak256(e) for a, e in zip(aliases, encs)}
    mapping[b"\xbb" * 32] = b"\xcc" * 32  # unrelated entries are inert
    assert m.rekey(mapping) == 3
    for enc in encs:
        assert m.get(keccak256(enc)) == enc
    assert m.verify() == 0


def test_drop_aliases_forgets_unpublished_rows():
    """A torn window's aliases are dropped, never promoted: a later
    rekey with the same placeholder bytes must move nothing."""
    from khipu_tpu.storage.device_mirror import TILE

    m = DeviceNodeMirror(capacity_rows_per_class=1024)
    encs = [b"\x5a" * 44]
    enc_dev, claim_dev = _device_tile(encs)
    aliases = [b"\xaa" * 32]
    m.admit_device(
        1, aliases + [None] * (TILE - 1), enc_dev, claim_dev,
        [44] + [0] * (TILE - 1),
    )
    m.drop_aliases(aliases)
    assert m.rekey({aliases[0]: keccak256(encs[0])}) == 0
    assert m.get(keccak256(encs[0])) is None


def test_node_storage_read_through_and_detach():
    """NodeStorage falls through to the mirror for not-yet-spilled
    nodes; recovery's detach makes the same read miss (the mirror is
    volatile — crash verification must see host-durable state only)."""
    from khipu_tpu.storage.storages import Storages

    storages = Storages()
    m = DeviceNodeMirror(capacity_rows_per_class=1024)
    enc = b"\x42" * 80
    h = keccak256(enc)
    m.admit({h: enc})
    m.flush()
    storages.attach_mirror(m)
    assert storages.account_node_storage.get(h) == enc
    assert storages.storage_node_storage.get(h) == enc
    assert storages.get_node_any(h) == enc
    storages.detach_mirror()
    assert storages.account_node_storage.get(h) is None
    assert storages.get_node_any(h) is None


def test_long_string_overflow_rejected():
    """Adversarial RLP length fields near PY_SSIZE_T_MAX must raise
    RLPError (not wrap around) in BOTH codecs."""
    import pytest as _pytest

    from khipu_tpu.base import rlp as R

    for bad in (
        b"\xbf" + b"\x7f" + b"\xff" * 7,           # huge string length
        b"\xff" + b"\x7f" + b"\xff" * 7,           # huge list length
        b"\xbf" + b"\x00\x10" + b"\xff" * 6,       # non-canonical lead 0
    ):
        with _pytest.raises(R.RLPError):
            R.rlp_decode(bad)
        with _pytest.raises(R.RLPError):
            R._py_rlp_decode(bad)
