"""Device-resident word-major node mirror (storage/device_mirror.py):
admit -> verify round trip, corruption detection, ring eviction, and
read-back. Runs on the CPU backend via the jnp sponge (same digests)."""

import random

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.storage.device_mirror import DeviceNodeMirror


@pytest.fixture(scope="module")
def mirror():
    m = DeviceNodeMirror(capacity_rows_per_class=1024)
    rng = random.Random(5)
    items = {}
    for _ in range(40):
        enc = rng.randbytes(rng.choice([70, 130, 300, 532]))
        items[keccak256(enc)] = enc
    m.admit(items)
    m.flush()
    return m, items


def test_verify_clean(mirror):
    m, items = mirror
    assert m.resident_count == len(items)
    assert m.verify() == 0


def test_read_back(mirror):
    m, items = mirror
    for h, enc in list(items.items())[:5]:
        assert m.contains(h)
        assert m.get(h) == enc
    assert m.get(b"\x00" * 32) is None


def test_corrupt_admit_detected():
    m = DeviceNodeMirror(capacity_rows_per_class=1024)
    enc = b"\xab" * 64
    m.admit({keccak256(enc): enc, b"\x99" * 32: b"\xcd" * 64})
    m.flush()
    assert m.verify() == 1  # exactly the forged claim fails


def test_ring_eviction():
    m = DeviceNodeMirror(capacity_rows_per_class=1024)
    items = {}
    for i in range(1500):
        enc = i.to_bytes(8, "big") * 9
        items[keccak256(enc)] = enc
    m.admit(items)
    m.flush()
    assert m.resident_count <= 1024
    assert m.verify() == 0  # evicted rows dropped, survivors intact


def test_exact_length_class():
    """Uniform-length populations store unpadded (in-kernel pad):
    verify and read-back must behave identically to the generic class."""
    import numpy as np

    rng = random.Random(11)
    m2 = DeviceNodeMirror(capacity_rows_per_class=1024)
    raw_full = np.frombuffer(
        rng.randbytes(64 * 1024), dtype=np.uint8
    ).reshape(1024, 64)
    hs = [keccak256(raw_full[i].tobytes()) for i in range(1024)]
    m2.admit_packed(hs, raw_full, [64] * 1024, exact=True)
    assert m2.verify() == 0
    assert m2.get(hs[0]) == raw_full[0].tobytes()
    assert m2.resident_count == 1024


def test_duplicate_admit_bookkeeping():
    """Re-admitting a resident hash must not inflate resident_count,
    and ring eviction of the OLD copy must not unmap the newer row."""
    m = DeviceNodeMirror(capacity_rows_per_class=1024)
    enc = b"\x77" * 64
    h = keccak256(enc)
    m.admit({h: enc})
    m.flush()
    assert m.resident_count == 1
    # duplicate admit via a fresh staging round (new tile, same hash)
    m.admit({h: enc})
    m.flush()
    assert m.resident_count == 1
    assert m.get(h) == enc
    assert m.verify() == 0


def test_long_string_overflow_rejected():
    """Adversarial RLP length fields near PY_SSIZE_T_MAX must raise
    RLPError (not wrap around) in BOTH codecs."""
    import pytest as _pytest

    from khipu_tpu.base import rlp as R

    for bad in (
        b"\xbf" + b"\x7f" + b"\xff" * 7,           # huge string length
        b"\xff" + b"\x7f" + b"\xff" * 7,           # huge list length
        b"\xbf" + b"\x00\x10" + b"\xff" * 6,       # non-canonical lead 0
    ):
        with _pytest.raises(R.RLPError):
            R.rlp_decode(bad)
        with _pytest.raises(R.RLPError):
            R._py_rlp_decode(bad)
