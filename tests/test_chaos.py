"""Deterministic fault injection + crash-consistent window commits
(khipu_tpu/chaos/, sync/journal.py — docs/recovery.md).

The headline scenarios: a simulated process death mid background
window commit followed by journal recovery resumes to a BIT-EXACT
chain vs an uninterrupted run; injected corruption on verified paths
is NEVER silently admitted (100+ seeded trials); a seeded FaultPlan
fires the identical fault sequence run after run.
"""

import dataclasses
import threading
import time

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.chaos import (
    FaultPlan,
    FaultRule,
    InjectedDeath,
    InjectedFault,
    active,
    fault_log,
    fault_point,
    fault_value,
)
from khipu_tpu.config import SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.storage.compactor import verify_reachable
from khipu_tpu.storage.datasource import MemoryKeyValueDataSource
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.journal import WindowJournal, recover
from khipu_tpu.sync.replay import (
    PIPELINE_GAUGES,
    CollectorDied,
    ReplayDriver,
)

pytestmark = pytest.mark.chaos

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(4)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18
MINER = b"\xaa" * 20
ALLOC = {a: 1000 * ETH for a in ADDRS}
N_BLOCKS = 12


def _tx(i, nonce, to, value):
    return sign_transaction(
        Transaction(nonce, 10**9, 21_000, to, value), KEYS[i], chain_id=1
    )


@pytest.fixture(scope="module")
def chain():
    """12 transfer blocks — enough windows for a depth-2 pipeline to
    have committed, in-flight AND un-sealed work when the fault hits."""
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG, GenesisSpec(alloc=ALLOC)
    )
    blocks = []
    nonces = [0, 0, 0, 0]
    for n in range(N_BLOCKS):
        i = n % len(KEYS)
        blocks.append(
            builder.add_block(
                [_tx(i, nonces[i], ADDRS[(i + 1) % 4], 100 + n)],
                coinbase=MINER,
            )
        )
        nonces[i] += 1
    return blocks


def _cfg(window=2, depth=2, degrade=True):
    # adaptive_commit off: chaos plans target fault seams on the
    # CONFIGURED path; the adaptive controller would route CPU runs to
    # host commit and the device seams would never fire
    return dataclasses.replace(
        CFG,
        sync=SyncConfig(
            parallel_tx=False,
            commit_window_blocks=window,
            pipeline_depth=depth,
            degrade_on_collector_death=degrade,
            collector_join_timeout=5.0,
            adaptive_commit=False,
        ),
    )


def _fresh(cfg):
    bc = Blockchain(Storages(), cfg)
    bc.load_genesis(GenesisSpec(alloc=ALLOC))
    return bc


def _clean_reference(chain, window=1):
    """Uninterrupted replay of the whole fixture — the oracle every
    crash/degrade scenario must be bit-exact against."""
    cfg = _cfg(window=window, depth=1)
    bc = _fresh(cfg)
    ReplayDriver(bc, cfg).replay(chain)
    return bc


def _assert_same_chain(bc, ref, upto=N_BLOCKS):
    assert bc.best_block_number == ref.best_block_number == upto
    for n in range(upto + 1):
        a, b = bc.get_header_by_number(n), ref.get_header_by_number(n)
        assert a is not None and a.hash == b.hash, f"block {n} diverged"
        assert a.state_root == b.state_root
    s = bc.storages
    walk = verify_reachable(
        s.account_node_storage, s.storage_node_storage,
        s.evmcode_storage,
        bc.get_header_by_number(upto).state_root, verify_hashes=True,
    )
    assert walk.missing == 0 and walk.corrupt == 0


# -------------------------------------------------------------- plan


class TestFaultPlan:
    def test_same_seed_same_fault_sequence(self):
        rules = [
            FaultRule("a.site", "latency", prob=0.3, latency_s=0.0),
            FaultRule("b.*", "latency", prob=0.5, latency_s=0.0),
        ]
        fired = []
        for _ in range(2):
            plan = FaultPlan(seed=42, rules=list(rules), sleep=lambda s: None)
            for i in range(200):
                plan.fire("a.site")
                plan.fire("b.other" if i % 3 else "b.site")
            fired.append(list(plan.fired))
        assert fired[0] == fired[1]
        assert len(fired[0]) > 10  # the rules actually fired

    def test_different_seed_different_sequence(self):
        def run(seed):
            plan = FaultPlan(
                seed=seed,
                rules=[FaultRule("s", "latency", prob=0.5, latency_s=0.0)],
                sleep=lambda s: None,
            )
            for _ in range(100):
                plan.fire("s")
            return list(plan.fired)

        assert run(1) != run(2)

    def test_after_and_times_windows(self):
        plan = FaultPlan(
            seed=0,
            rules=[FaultRule("s", "latency", after=3, times=2,
                             latency_s=0.0)],
            sleep=lambda s: None,
        )
        for _ in range(10):
            plan.fire("s")
        assert [hit for (_, hit, _, _) in plan.fired] == [4, 5]

    def test_raise_and_die_kinds(self):
        plan = FaultPlan(seed=0, rules=[FaultRule("r", "raise")])
        with pytest.raises(InjectedFault):
            plan.fire("r")
        plan = FaultPlan(seed=0, rules=[FaultRule("d", "die")])
        with pytest.raises(InjectedDeath):
            plan.fire("d")
        # die must NOT be an ordinary Exception (generic recovery
        # would swallow a simulated process death)
        assert not issubclass(InjectedDeath, Exception)

    def test_corrupt_flips_exactly_one_bit(self):
        plan = FaultPlan(seed=7, rules=[FaultRule("c", "corrupt")])
        original = bytes(range(64))
        out = plan.fire("c", original)
        assert out != original and len(out) == len(original)
        diff = [a ^ b for a, b in zip(original, out)]
        changed = [d for d in diff if d]
        assert len(changed) == 1
        assert bin(changed[0]).count("1") == 1

    def test_disabled_seams_are_identity(self):
        blob = b"untouched"
        assert fault_value("nowhere", blob) is blob
        fault_point("nowhere")  # no plan installed: no effect

    def test_active_context_installs_and_uninstalls(self):
        from khipu_tpu.chaos import plan as plan_mod

        with active(FaultPlan(seed=0, rules=[FaultRule("x", "raise")])):
            with pytest.raises(InjectedFault):
                fault_point("x")
        assert plan_mod._PLAN is None
        fault_point("x")  # uninstalled: inert again


# ----------------------------------------------------------- journal


class TestWindowJournal:
    def test_intent_commit_pending_roundtrip(self):
        j = WindowJournal(MemoryKeyValueDataSource())
        r1, r2 = b"\x11" * 32, b"\x22" * 32
        seq = j.log_intent(1, 2, b"\x00" * 32, [r1, r2])
        assert [p.seq for p in j.pending()] == [seq]
        rec = j.pending()[0]
        assert (rec.lo, rec.hi) == (1, 2)
        assert rec.roots == [r1, r2]
        assert rec.parent_root == b"\x00" * 32
        j.log_commit(seq)
        assert j.pending() == []

    def test_roots_must_cover_the_window(self):
        j = WindowJournal(MemoryKeyValueDataSource())
        with pytest.raises(ValueError):
            j.log_intent(1, 3, b"\x00" * 32, [b"\x11" * 32])

    def test_prune_stops_at_first_pending(self):
        j = WindowJournal(MemoryKeyValueDataSource())
        seqs = [
            j.log_intent(n, n, b"\x00" * 32, [bytes([n]) * 32])
            for n in range(1, 5)
        ]
        j.log_commit(seqs[0])
        j.log_commit(seqs[1])
        j.log_commit(seqs[3])  # out of order: 2 still pending
        assert j.prune() == 2  # only the settled PREFIX goes
        assert [p.seq for p in j.pending()] == [seqs[2]]
        assert j.depth == 2  # seqs 2..3 still live
        j.log_commit(seqs[2])
        assert j.prune() == 2
        assert j.depth == 0

    def test_clean_recover_is_a_noop(self, chain):
        cfg = _cfg()
        bc = _fresh(cfg)
        ReplayDriver(bc, cfg).replay(chain)
        best = bc.best_block_number
        report = recover(bc)
        assert report.clean and report.best_after == best
        assert bc.best_block_number == best


# ---------------------------------------------------- crash recovery


class TestCrashRecovery:
    def test_kill_mid_window_recover_resume_bit_exact(self, chain):
        """THE acceptance scenario: simulated process death mid
        background save of window [5..6] at pipeline depth 2; restart
        scans the journal, rolls the torn window back, and the resumed
        replay lands on a bit-exact chain vs an uninterrupted run."""
        cfg = _cfg(window=2, depth=2, degrade=False)
        bc = _fresh(cfg)
        # die after the 4th save_block: the collector is killed right
        # after persisting block 5, with block 6 of the same window
        # (and the window's commit mark) still unwritten
        plan = FaultPlan(
            seed=3, rules=[FaultRule("collector.save", "die", after=4,
                                     times=1)]
        )
        with active(plan):
            with pytest.raises(CollectorDied):
                ReplayDriver(bc, cfg).replay(chain)
        assert [s for (s, _, _, _) in plan.fired] == ["collector.save"]
        # the torn write IS visible pre-recovery: block 5 saved, 6 not
        assert bc.storages.app_state.best_block_number == 5
        assert bc.get_header_by_number(6) is None

        # "restart": a fresh driver over the SAME storages runs the
        # startup recovery pass
        driver = ReplayDriver(bc, cfg)
        report = driver.recover()
        assert report.scanned >= 1
        assert report.rolled_back >= 1
        assert report.best_after == 4  # last fully-committed window
        assert bc.best_block_number == 4
        assert bc.get_header_by_number(5) is None  # partial save undone
        assert bc.storages.window_journal.pending() == []

        # resume where recovery left off, serial path, no faults
        resume_cfg = _cfg(window=1, depth=1)
        ReplayDriver(bc, resume_cfg).replay(chain[4:])
        _assert_same_chain(bc, _clean_reference(chain))

    def test_death_after_saves_before_mark_repairs(self, chain):
        """Death BETWEEN the last save and the commit mark: the window
        is fully persisted, only the mark is missing — recovery must
        re-verify and REPAIR (restore the mark), not roll back."""
        cfg = _cfg(window=2, depth=2, degrade=False)
        bc = _fresh(cfg)
        plan = FaultPlan(
            seed=5, rules=[FaultRule("collector.commit", "die", after=2,
                                     times=1)]
        )
        with active(plan):
            with pytest.raises(CollectorDied):
                ReplayDriver(bc, cfg).replay(chain)
        assert bc.storages.app_state.best_block_number == 6

        report = ReplayDriver(bc, cfg).recover()
        assert report.repaired >= 1
        assert report.best_after == 6  # nothing to undo
        assert bc.storages.window_journal.pending() == []

        resume_cfg = _cfg(window=1, depth=1)
        ReplayDriver(bc, resume_cfg).replay(chain[6:])
        _assert_same_chain(bc, _clean_reference(chain))

    def test_kill_mid_spill_recover_resume_bit_exact(self, chain):
        """Death INSIDE the async spill (collector.spill fires between
        the account-store and storage-store writes of the persist
        stage): the window's nodes are half-spilled and no block of it
        saved. Recovery must roll the torn window back bit-exact —
        content-addressed orphans from the half spill are harmless."""
        cfg = _cfg(window=2, depth=2, degrade=False)
        bc = _fresh(cfg)
        plan = FaultPlan(
            seed=7, rules=[FaultRule("collector.spill", "die", after=2,
                                     times=1)]
        )
        with active(plan):
            with pytest.raises(CollectorDied):
                ReplayDriver(bc, cfg).replay(chain)
        assert [s for (s, _, _, _) in plan.fired] == ["collector.spill"]

        driver = ReplayDriver(bc, cfg)
        report = driver.recover()
        assert report.scanned >= 1
        assert report.rolled_back >= 1
        assert bc.storages.window_journal.pending() == []

        resume_cfg = _cfg(window=1, depth=1)
        ReplayDriver(bc, resume_cfg).replay(
            chain[bc.best_block_number:]
        )
        _assert_same_chain(bc, _clean_reference(chain))

    def test_kill_between_seal_and_pack_rolls_back(self, chain):
        """Death ON the new driver->seal-stage boundary: the driver
        already fsynced the window's journal intent and handed the job
        off, but the seal stage dies BEFORE the pack scan touches
        anything. Nothing of the window is durable, so recovery sees a
        bare intent and rolls it back; the resume lands bit-exact."""
        cfg = _cfg(window=2, depth=2, degrade=False)
        bc = _fresh(cfg)
        plan = FaultPlan(
            seed=13, rules=[FaultRule("collector.seal", "die", after=2,
                                      times=1)]
        )
        with active(plan):
            with pytest.raises(CollectorDied):
                ReplayDriver(bc, cfg).replay(chain)
        assert [s for (s, _, _, _) in plan.fired] == ["collector.seal"]

        report = ReplayDriver(bc, cfg).recover()
        assert report.scanned >= 1
        assert report.rolled_back >= 1
        assert bc.storages.window_journal.pending() == []
        resume_cfg = _cfg(window=1, depth=1)
        ReplayDriver(bc, resume_cfg).replay(
            chain[bc.best_block_number:]
        )
        _assert_same_chain(bc, _clean_reference(chain))

    def test_kill_mid_pack_rolls_back(self, chain):
        """Death INSIDE the off-driver pack (collector.pack fires after
        the placeholder scan, before the fused dispatch): the window's
        encodings were read but nothing was dispatched or persisted.
        The intent fsynced on the driver before handoff makes the torn
        window visible to recovery, which rolls it back."""
        cfg = _cfg(window=2, depth=2, degrade=False)
        bc = _fresh(cfg)
        plan = FaultPlan(
            seed=17, rules=[FaultRule("collector.pack", "die", after=1,
                                      times=1)]
        )
        with active(plan):
            with pytest.raises(CollectorDied):
                ReplayDriver(bc, cfg).replay(chain)
        assert [s for (s, _, _, _) in plan.fired] == ["collector.pack"]

        report = ReplayDriver(bc, cfg).recover()
        assert report.scanned >= 1
        assert report.rolled_back >= 1
        assert bc.storages.window_journal.pending() == []
        resume_cfg = _cfg(window=1, depth=1)
        ReplayDriver(bc, resume_cfg).replay(
            chain[bc.best_block_number:]
        )
        _assert_same_chain(bc, _clean_reference(chain))

    def test_kill_between_persist_and_save_rolls_back(self, chain):
        """Death ON the persist->save stage boundary: the window's
        nodes are fully spilled but no block record exists and the
        commit mark is missing. The journal contract holds — the
        window is NOT durable until persist AND save completed, so
        recovery rolls it back (node orphans are content-addressed
        noise) and the resume lands bit-exact."""
        cfg = _cfg(window=2, depth=2, degrade=False)
        bc = _fresh(cfg)
        # 'after=2, times=1': the 3rd window entering its save stage
        # dies before its first save_block
        plan = FaultPlan(
            seed=11, rules=[FaultRule("collector.save", "die", after=4,
                                      times=1)]
        )
        with active(plan):
            with pytest.raises(CollectorDied):
                ReplayDriver(bc, cfg).replay(chain)

        report = ReplayDriver(bc, cfg).recover()
        assert report.rolled_back >= 1
        assert bc.storages.window_journal.pending() == []
        resume_cfg = _cfg(window=1, depth=1)
        ReplayDriver(bc, resume_cfg).replay(
            chain[bc.best_block_number:]
        )
        _assert_same_chain(bc, _clean_reference(chain))

    def test_service_board_runs_recovery_on_boot(self, chain):
        """ServiceBoard's __init__ settles pending intents before any
        service starts (the operator-facing restart path)."""
        from khipu_tpu.service_board import ServiceBoard

        cfg = _cfg(window=2, depth=2, degrade=False)
        bc = _fresh(cfg)
        plan = FaultPlan(
            seed=3, rules=[FaultRule("collector.save", "die", after=4,
                                     times=1)]
        )
        with active(plan):
            with pytest.raises(CollectorDied):
                ReplayDriver(bc, cfg).replay(chain)
        # rebind the crashed node's storages onto a fresh board (the
        # memory engine's restart analog)
        board = ServiceBoard.__new__(ServiceBoard)
        board.config = cfg
        board.storages = bc.storages
        board.blockchain = Blockchain(bc.storages, cfg)
        board.recovery_report = None
        if cfg.sync.commit_journal:
            if board.storages.window_journal.pending():
                board.recovery_report = recover(board.blockchain)
        assert board.recovery_report is not None
        assert board.recovery_report.rolled_back >= 1
        assert board.blockchain.best_block_number == 4


# ------------------------------ die inside the vectorized fast path


class TestExecuteBatchDeath:
    """``ledger.batch`` fires per scatter row of the vectorized fast
    path — ON THE DRIVER THREAD, mid-block, with the batch's world
    half-scattered. The torn world is memory-only: nothing of the
    dying block is durable, so recovery rolls back to the last
    committed window and a serial resume lands bit-exact."""

    def _sched_cfg(self, window=2, depth=2):
        # the scheduled path needs parallel_tx (the module _cfg runs
        # serial so the collector seams fire deterministically)
        return dataclasses.replace(
            CFG,
            sync=SyncConfig(
                parallel_tx=True,
                commit_window_blocks=window,
                pipeline_depth=depth,
                degrade_on_collector_death=False,
                collector_join_timeout=5.0,
                adaptive_commit=False,
            ),
        )

    @pytest.fixture(scope="class")
    def wide_chain(self):
        """12 blocks x 2 DISJOINT transfers: every block takes the
        scheduled fast path (single-tx blocks dispatch sequential and
        would never reach the ``ledger.batch`` seam)."""
        builder = ChainBuilder(
            Blockchain(Storages(), CFG), CFG, GenesisSpec(alloc=ALLOC)
        )
        blocks = []
        nonces = [0, 0, 0, 0]
        for n in range(N_BLOCKS):
            a, b = n % 2, 2 + n % 2  # disjoint sender pair
            txs = []
            for i, tag in ((a, 0xBEEF0000), (b, 0xFEED0000)):
                to = (tag + n).to_bytes(4, "big").rjust(20, b"\x00")
                txs.append(_tx(i, nonces[i], to, 100 + n))
                nonces[i] += 1
            blocks.append(builder.add_block(txs, coinbase=MINER))
        return blocks

    def test_die_mid_batch_recover_serial_resume_bit_exact(
        self, wide_chain
    ):
        cfg = self._sched_cfg()
        bc = _fresh(cfg)
        # 2 scatter rows per block: after=6 kills the driver on block
        # 4's FIRST row — sender 1 already debited, recipient not yet
        # credited, window [3..4] un-sealed
        plan = FaultPlan(
            seed=11, rules=[FaultRule("ledger.batch", "die", after=6,
                                      times=1)]
        )
        with active(plan):
            # the fault fires in foreground execute, so the death
            # surfaces directly (NOT CollectorDied — the collector is
            # an innocent bystander the driver tears down on the way)
            with pytest.raises(InjectedDeath):
                ReplayDriver(bc, cfg).replay(wide_chain)
        assert [s for (s, _, _, _) in plan.fired] == ["ledger.batch"]
        # nothing of the torn block is durable
        assert bc.best_block_number < 4

        report = ReplayDriver(bc, cfg).recover()
        assert report.best_after == bc.best_block_number
        assert bc.storages.window_journal.pending() == []

        # resume on the SERIAL path: recovery must not depend on the
        # scheduler that was running when the process died
        resume_cfg = _cfg(window=1, depth=1)
        ReplayDriver(bc, resume_cfg).replay(
            wide_chain[bc.best_block_number:]
        )
        _assert_same_chain(bc, _clean_reference(wide_chain))


# ----------------------------------------------- graceful degradation


class TestDegrade:
    def test_collector_death_degrades_to_sync_commits(self, chain):
        """Default posture: a dead collector does NOT abort the replay
        — the driver re-runs the torn job and commits the rest of the
        windows synchronously, landing on the bit-exact chain."""
        cfg = _cfg(window=2, depth=2, degrade=True)
        bc = _fresh(cfg)
        deaths0 = PIPELINE_GAUGES["collector_deaths"]
        sync0 = PIPELINE_GAUGES["sync_fallback_windows"]
        plan = FaultPlan(
            seed=1, rules=[FaultRule("collector.collect", "die", after=1,
                                     times=1)]
        )
        with active(plan):
            stats = ReplayDriver(bc, cfg).replay(chain)
        assert stats.blocks == N_BLOCKS
        assert PIPELINE_GAUGES["collector_deaths"] == deaths0 + 1
        assert PIPELINE_GAUGES["sync_fallback_windows"] > sync0
        _assert_same_chain(bc, _clean_reference(chain))

    def test_persist_stage_death_degrades_to_sync_commits(self, chain):
        """A death on the collect->persist stage boundary (the job
        already rootchecked, its spill never started) degrades the
        driver to synchronous commits; the torn job's remaining stages
        re-run inline and the chain lands bit-exact."""
        cfg = _cfg(window=2, depth=2, degrade=True)
        bc = _fresh(cfg)
        deaths0 = PIPELINE_GAUGES["collector_deaths"]
        plan = FaultPlan(
            seed=4, rules=[FaultRule("collector.persist", "die",
                                     after=1, times=1)]
        )
        with active(plan):
            stats = ReplayDriver(bc, cfg).replay(chain)
        assert stats.blocks == N_BLOCKS
        assert PIPELINE_GAUGES["collector_deaths"] == deaths0 + 1
        _assert_same_chain(bc, _clean_reference(chain))

    def test_fused_dispatch_failure_falls_back_to_host(self, chain):
        """A runtime device failure at fused dispatch degrades THAT
        window to the host hasher (metric + warning) instead of killing
        the replay; roots still gate every block."""
        from khipu_tpu.ledger.window import WINDOW_GAUGES
        from khipu_tpu.trie.bulk import host_hasher

        cfg = _cfg(window=2, depth=2)
        bc = _fresh(cfg)
        driver = ReplayDriver(bc, cfg, device_commit=True)
        driver.hasher = host_hasher  # fused seal path, host fallback
        falls0 = WINDOW_GAUGES["fused_fallbacks"]
        # the raise fires at the fault_point BEFORE any device work, so
        # this exercises the degrade branch without an XLA compile
        plan = FaultPlan(seed=2, rules=[FaultRule("fused.dispatch",
                                                  "raise")])
        with active(plan):
            stats = driver.replay(chain)
        assert stats.blocks == N_BLOCKS
        assert WINDOW_GAUGES["fused_fallbacks"] > falls0
        _assert_same_chain(bc, _clean_reference(chain))

    def test_collector_close_raises_on_wedged_worker(self):
        from khipu_tpu.sync.replay import _WindowCollector

        release = threading.Event()
        collector = _WindowCollector(1, join_timeout=0.2)
        collector.submit(lambda: release.wait(10))
        with pytest.raises(RuntimeError, match="failed to stop"):
            collector.close()
        release.set()
        collector._thread.join(timeout=5)


# ----------------------------------------------- fail-stop handler audit


class TestFailStopHandlerAudit:
    """KL002 audit (docs/static_analysis.md): sync/replay.py keeps two
    broad ``except BaseException`` handlers on purpose. This class pins
    the property the pragma annotations claim — a chaos ``die``
    (InjectedDeath) inside each still fail-stops instead of being
    swallowed into a recoverable-looking error."""

    def test_die_in_collect_job_is_not_recorded_as_failure(self):
        """Worker-side handler (_WindowCollector._run): InjectedDeath
        must take the dedicated death path — the thread just stops with
        NO ``_failure`` record (recording it would downgrade a process
        death to an ordinary abort that submit() re-raises), and the
        torn job stays current so take_pending can re-run it."""
        from khipu_tpu.sync.replay import _WindowCollector

        collector = _WindowCollector(2, join_timeout=5.0)

        def torn_job():
            raise InjectedDeath("die inside collect job")

        collector.submit(torn_job)
        collector._thread.join(timeout=5)
        assert not collector._thread.is_alive()
        # SIGKILL semantics: death is NOT a recorded failure ...
        assert collector._failure is None
        # ... the driver learns of it through the liveness check ...
        with pytest.raises(CollectorDied):
            collector.submit(lambda: None)
        # ... and the half-done job is first in line for the re-run
        assert collector.take_pending() == [torn_job]

    def test_die_at_fused_dispatch_escapes_replay(self, chain):
        """Driver-side handler (ReplayDriver.replay): a ``die`` at the
        fused.dispatch fault point must NOT be absorbed by the
        per-window host-fallback catch (``except Exception`` — too
        narrow for BaseException by design); the driver's broad handler
        kills the pipeline and re-raises, so the simulated process
        death escapes replay() instead of degrading."""
        from khipu_tpu.trie.bulk import host_hasher

        cfg = _cfg(window=2, depth=2)
        bc = _fresh(cfg)
        driver = ReplayDriver(bc, cfg, device_commit=True)
        driver.hasher = host_hasher  # fires before any XLA compile
        plan = FaultPlan(
            seed=3, rules=[FaultRule("fused.dispatch", "die")]
        )
        with active(plan):
            with pytest.raises(InjectedDeath):
                driver.replay(chain)
        # fail-stop: the chain stops strictly short of the fixture tip
        assert bc.best_block_number < N_BLOCKS


# ------------------------------------------------------ serving chaos


class TestServingUnderCollectorDeath:
    def test_collector_dies_under_load_sheds_no_torn_reads(self, chain):
        """The serving-plane chaos scenario (docs/serving.md): mixed
        RPC load drives a node mid-import, the window collector DIES
        under it, and the degrade path takes over. Required outcomes:
        the write backlog trips pressure shedding (-32005) instead of
        unbounded queueing, the read-your-writes checker sees zero
        regressions across the death (no torn-window reads), and the
        chain the degraded import lands on is bit-exact."""
        from khipu_tpu.config import ServingConfig
        from khipu_tpu.jsonrpc import EthService, JsonRpcServer
        from khipu_tpu.serving import AdmissionController, ReadView, ServingPlane
        from khipu_tpu.serving.admission import (
            pipeline_pressure,
            txpool_pressure,
        )
        from khipu_tpu.serving.loadgen import (
            MIXED,
            InProcessTransport,
            LoadGenerator,
        )
        from khipu_tpu.txpool import PendingTransactionsPool

        cfg = dataclasses.replace(
            _cfg(window=2, depth=2, degrade=True),
            serving=ServingConfig(queue_timeout=0.01, max_queue=8),
        )
        bc = _fresh(cfg)
        rv = ReadView(bc)
        # tiny pool: the MIXED profile's write stream (~10%) fills it
        # mid-run, so pressure shedding MUST kick in under this load
        pool = PendingTransactionsPool(capacity=24)
        plane = ServingPlane(
            cfg.serving, read_view=rv,
            admission=AdmissionController(
                cfg.serving,
                signals=[pipeline_pressure(), txpool_pressure(pool)],
            ),
        )
        service = EthService(bc, cfg, pool, read_view=rv, serving=plane)
        server = JsonRpcServer(service, serving=plane)

        deaths0 = PIPELINE_GAUGES["collector_deaths"]
        sync0 = PIPELINE_GAUGES["sync_fallback_windows"]

        def throttled():
            for b in chain:
                yield b
                time.sleep(0.005)

        result = {}

        def run_sync():
            plan = FaultPlan(
                seed=9,
                rules=[FaultRule("collector.collect", "die", after=1,
                                 times=1)],
            )
            with active(plan):
                result["stats"] = ReplayDriver(
                    bc, cfg, read_view=rv
                ).replay(throttled())

        sync_thread = threading.Thread(target=run_sync, daemon=True)
        sync_thread.start()
        report = LoadGenerator(
            InProcessTransport(server), MIXED, clients=4,
            max_requests=150, seed=5,
            nonce_addresses=["0x" + a.hex() for a in ADDRS],
            # the only accumulate-only address in this fixture: senders
            # pay fees, so their balances legitimately move both ways
            balance_addresses=["0x" + MINER.hex()],
            chain_id=1,
        ).run()
        sync_thread.join(timeout=60)
        assert not sync_thread.is_alive()

        # import survived the death via the degrade path
        assert result["stats"].blocks == N_BLOCKS
        assert PIPELINE_GAUGES["collector_deaths"] == deaths0 + 1
        assert PIPELINE_GAUGES["sync_fallback_windows"] > sync0
        # shed rate rose: the backlog tripped pressure sheds (-32005)
        assert report.shed > 0
        snap = plane.admission.snapshot()
        assert snap["write"]["shed"]["pressure"] > 0
        # zero read-your-writes violations across the death: no torn
        # or backwards state was ever served
        assert report.violations == [], report.violations[:5]
        assert report.ok > 0
        # the overlay drained: reads now resolve at the durable head
        assert rv.head_number() == bc.best_block_number == N_BLOCKS
        assert rv.snapshot()["overlayAddrs"] == 0
        # and the degraded chain is bit-exact vs the clean oracle
        _assert_same_chain(bc, _clean_reference(chain))


# ------------------------------------------------------ cluster chaos


class FakeShard:
    """In-memory BridgeClient stand-in (tests/test_cluster.py shape)."""

    def __init__(self, store=None, fail=False):
        self.store = dict(store or {})
        self.fail = fail

    def get_node_data(self, hashes):
        if self.fail:
            raise ConnectionError("shard down")
        return {h: self.store[h] for h in hashes if h in self.store}

    def put_node_data(self, nodes):
        if self.fail:
            raise ConnectionError("shard down")
        self.store.update(nodes)
        return len(nodes)

    def ping(self, payload=b""):
        if self.fail:
            raise ConnectionError("shard down")
        return payload

    def close(self):
        pass


def _make_client(shards, **kwargs):
    from khipu_tpu.cluster import ShardedNodeClient

    kwargs.setdefault("replication", 2)
    kwargs.setdefault("max_retries", 1)
    kwargs.setdefault("sleep", lambda s: None)
    return ShardedNodeClient(
        list(shards), channel_factory=lambda ep: shards[ep], **kwargs
    )


def _nodes(n, tag=0):
    out = {}
    for i in range(n):
        v = b"node-" + tag.to_bytes(2, "big") + i.to_bytes(4, "big") * 5
        out[keccak256(v)] = v
    return out


class TestStagedPipelineSweep:
    def test_stage_boundary_die_sweep_120_seeds(self, chain):
        """The async-spill analog of the 120-seed corruption sweep:
        seeded deaths across every stage boundary of the staged
        collector (seal-stage entry -> mid-pack -> rootcheck/admit ->
        spill -> save -> commit mark, plus the mid-spill seam).
        Whatever the seed kills, journal recovery plus a serial resume
        must land on the bit-exact chain — a torn window is NEVER
        silently half-durable."""
        sites = ("collector.seal", "collector.pack",
                 "collector.collect", "collector.persist",
                 "collector.spill", "collector.save",
                 "collector.commit")
        ref = _clean_reference(chain)
        killed = survived = 0
        for seed in range(120):
            site = sites[seed % len(sites)]
            cfg = _cfg(window=2, depth=2, degrade=False)
            bc = _fresh(cfg)
            # deterministic depth: die on the k-th visit to the site;
            # k beyond the run's visit count = a clean, uninterrupted
            # replay (both outcomes exercised across the sweep)
            plan = FaultPlan(
                seed=seed,
                rules=[FaultRule(site, "die", times=1,
                                 after=(seed // len(sites)) % 14)],
            )
            with active(plan):
                try:
                    ReplayDriver(bc, cfg).replay(chain)
                    survived += 1
                except CollectorDied:
                    killed += 1
                    ReplayDriver(bc, cfg).recover()
                    assert bc.storages.window_journal.pending() == []
            if bc.best_block_number < N_BLOCKS:
                resume_cfg = _cfg(window=1, depth=1)
                ReplayDriver(bc, resume_cfg).replay(
                    chain[bc.best_block_number:]
                )
            _assert_same_chain(bc, ref)
        # the harness genuinely exercised both outcomes
        assert killed > 20 and survived > 20, (killed, survived)


class TestClusterChaos:
    def test_injected_corruption_never_admitted_100_seeds(self):
        """THE zero-silent-acceptance gate: across 120 seeded trials,
        every corrupt fault fired on the cluster fetch path is caught
        by content-address verification — a returned value ALWAYS
        keccak-matches its key, and every fired fault shows up in the
        corrupt counters."""
        nodes = _nodes(20)
        total_fired = 0
        for seed in range(120):
            shards = {ep: FakeShard(dict(nodes)) for ep in ("a", "b")}
            cl = _make_client(shards, replication=1, max_retries=0)
            plan = FaultPlan(
                seed=seed,
                rules=[FaultRule("cluster.fetch.value", "corrupt",
                                 prob=0.5)],
            )
            with active(plan):
                got = cl.fetch(list(nodes))
            fired = len(plan.fired)
            total_fired += fired
            for h, v in got.items():
                assert keccak256(v) == h, f"seed {seed}: corrupt admitted"
            corrupt_counted = sum(
                m.corrupt for m in cl.metrics.values()
            )
            assert corrupt_counted == fired, (
                f"seed {seed}: {fired} fired, {corrupt_counted} caught"
            )
            assert len(got) + corrupt_counted == len(nodes)
        assert total_fired > 100  # the harness genuinely exercised it

    def test_corrupt_healed_from_honest_replica(self):
        """With replication=2 a corrupted primary read fails over and
        the honest replica still serves the true bytes."""
        nodes = _nodes(8)
        shards = {ep: FakeShard(dict(nodes)) for ep in ("a", "b", "c")}
        cl = _make_client(shards, replication=2)
        plan = FaultPlan(
            seed=9,
            rules=[FaultRule("cluster.fetch.value", "corrupt", times=3)],
        )
        with active(plan):
            got = cl.fetch(list(nodes))
        assert got == nodes  # every key healed
        assert sum(m.corrupt for m in cl.metrics.values()) == 3

    def test_injected_rpc_faults_drive_retry_and_failover(self):
        nodes = _nodes(6)
        shards = {ep: FakeShard(dict(nodes)) for ep in ("a", "b")}
        cl = _make_client(shards, replication=2, max_retries=0)
        plan = FaultPlan(
            seed=4, rules=[FaultRule("cluster.call:a", "raise")]
        )
        with active(plan):
            got = cl.fetch(list(nodes))
        assert got == nodes  # b served everything a's faults dropped
        assert cl.metrics["a"].failures > 0

    def test_rejoin_triggers_anti_entropy_backfill(self):
        """ROADMAP item: keys written while an endpoint was out of the
        ring are re-replicated onto it when the HealthMonitor flips it
        dead -> alive."""
        from khipu_tpu.cluster import HealthMonitor

        shards = {ep: FakeShard() for ep in ("a", "b", "c")}
        cl = _make_client(shards, replication=2)
        mon = HealthMonitor(cl, down_after=1, up_after=1)

        cl.replicate(_nodes(10, tag=1))  # all alive: no debt
        assert cl._missed_total == 0

        shards["b"].fail = True
        mon.probe_once()
        assert "b" not in cl.ring.members

        missed_batch = _nodes(40, tag=2)
        cl.replicate(missed_batch)
        owed = [
            h for h in missed_batch
            if "b" in cl._full_ring.replicas_for(h)
        ]
        assert owed, "fixture must place some keys on b"
        assert cl._missed_total >= len(owed)
        before = set(shards["b"].store)

        shards["b"].fail = False
        mon.probe_once()  # re-join fires the backfill
        assert "b" in cl.ring.members
        assert cl.metrics["b"].backfilled >= len(owed)
        for h in owed:
            assert shards["b"].store.get(h) == missed_batch[h]
        assert cl._missed.get("b") in (None, {})
        snap = cl.metrics_snapshot()
        assert snap["missedKeys"] == cl._missed_total
        assert snap["shards"]["b"]["backfilled"] >= len(owed)
        assert set(shards["b"].store) >= before

    def test_missed_debt_is_bounded(self):
        shards = {ep: FakeShard() for ep in ("a", "b")}
        cl = _make_client(shards, replication=2, missed_cap=5)
        cl._record_missed("a", [bytes([i]) * 32 for i in range(9)])
        assert cl._missed_total == 5
        assert cl.missed_dropped == 4
        assert cl.metrics_snapshot()["missedDropped"] == 4


# ---------------------------------------------------- bridge deadline


class TestBridgeDeadline:
    def test_injected_latency_trips_rpc_deadline(self, chain):
        """A slow shard (latency fault on the served Ping) must surface
        as DEADLINE_EXCEEDED through the per-RPC deadline instead of
        blocking the caller."""
        grpc = pytest.importorskip("grpc")
        from khipu_tpu.bridge import BridgeClient, BridgeServer

        cfg = _cfg(window=1, depth=1)
        bc = _fresh(cfg)
        server = BridgeServer(bc, cfg)
        port = server.start(port=0)
        slow = BridgeClient(f"127.0.0.1:{port}", deadline=0.2)
        patient = BridgeClient(f"127.0.0.1:{port}", deadline=5.0)
        try:
            assert patient.ping(b"ok") == b"ok"  # server is up
            plan = FaultPlan(
                seed=0,
                rules=[FaultRule("bridge.serve.Ping", "latency",
                                 latency_s=1.5)],
            )
            with active(plan):
                t0 = time.monotonic()
                with pytest.raises(grpc.RpcError) as err:
                    slow.ping(b"late")
                assert err.value.code() == (
                    grpc.StatusCode.DEADLINE_EXCEEDED
                )
                # the deadline cut the wait well under the injected lag
                assert time.monotonic() - t0 < 1.2
        finally:
            slow.close()
            patient.close()
            server.stop()

    def test_corrupt_node_fetch_rejected_end_to_end(self, chain):
        """Corruption injected on the BridgeClient fetch path: the
        sharded client's admission check refuses the bytes even though
        the transport delivered them."""
        pytest.importorskip("grpc")
        from khipu_tpu.bridge import BridgeClient, BridgeServer

        cfg = _cfg(window=1, depth=1)
        bc = _fresh(cfg)
        ReplayDriver(bc, cfg).replay(chain)
        root = bc.get_header_by_number(N_BLOCKS).state_root
        server = BridgeServer(bc, cfg)
        port = server.start(port=0)
        client = BridgeClient(f"127.0.0.1:{port}", deadline=5.0)
        try:
            clean = client.get_node_data([root])
            assert keccak256(clean[root]) == root
            plan = FaultPlan(
                seed=11,
                rules=[FaultRule("bridge.node.value", "corrupt")],
            )
            with active(plan):
                tainted = client.get_node_data([root])
            assert keccak256(tainted[root]) != root  # seam really fired
            # ...and the cluster client over the same transport refuses
            # to admit it
            from khipu_tpu.cluster import ShardedNodeClient

            cl = ShardedNodeClient(
                [f"127.0.0.1:{port}"], replication=1, max_retries=0,
                channel_factory=lambda ep: BridgeClient(ep, deadline=5.0),
                sleep=lambda s: None,
            )
            with active(FaultPlan(seed=11, rules=[
                    FaultRule("bridge.node.value", "corrupt")])):
                got = cl.fetch([root])
            assert got == {}
            assert sum(m.corrupt for m in cl.metrics.values()) == 1
            cl.close()
        finally:
            client.close()
            server.stop()


# -------------------------------------------------------- determinism


class TestDeterminism:
    def test_replay_under_empty_plan_is_bit_exact(self, chain):
        """An installed-but-ruleless plan must not perturb replay — the
        zero-cost-disabled contract extends to 'armed but silent'."""
        cfg = _cfg(window=2, depth=2)
        a = _fresh(cfg)
        with active(FaultPlan(seed=99, rules=[])):
            ReplayDriver(a, cfg).replay(chain)
        _assert_same_chain(a, _clean_reference(chain))

    def test_seeded_replay_fires_identically_run_to_run(self, chain):
        """Same seed + same workload => same fired-fault log AND same
        final chain, twice over."""
        def run():
            cfg = _cfg(window=2, depth=2)
            bc = _fresh(cfg)
            plan = FaultPlan(
                seed=1234,
                rules=[
                    FaultRule("storage.node.get", "latency", prob=0.01,
                              latency_s=0.0),
                    FaultRule("collector.persist", "latency", prob=0.5,
                              latency_s=0.0),
                ],
                sleep=lambda s: None,
            )
            with active(plan):
                ReplayDriver(bc, cfg).replay(chain)
            return plan.fired, bc.get_header_by_number(
                N_BLOCKS
            ).state_root

        fired1, root1 = run()
        fired2, root2 = run()
        assert fired1 == fired2
        assert root1 == root2
        assert len(fired1) > 0

    def test_fault_log_snapshot_counts(self):
        fault_log.reset()
        plan = FaultPlan(
            seed=0,
            rules=[FaultRule("m.one", "latency", latency_s=0.0,
                             times=3)],
            sleep=lambda s: None,
        )
        with active(plan):
            for _ in range(5):
                fault_point("m.one")
        snap = fault_log.snapshot()
        assert snap["fired"] == 3
        assert snap["byKind"]["latency"] == 3
        assert snap["bySite"]["m.one"] == 3
        assert len(fault_log.recent()) == 3
        fault_log.reset()


# ------------------------- die inside the storage layer (save_block)


class TestStorageLayerDeath:
    def test_die_mid_save_block_torn_record_recovers(self, chain):
        """Death INSIDE save_block, between two block-store puts: the
        header of block 1 lands, its body never does — a torn RECORD,
        one level below the torn-window case. Startup recovery must
        treat the half-written block as part of the torn window and
        roll it back; the resumed replay is bit-exact."""
        cfg = _cfg(window=2, depth=2, degrade=False)
        bc = _fresh(cfg)
        # save_block's put order is header, body, receipts, td
        # (domain/blockchain.py); after=1 dies on the BODY put of the
        # first saved block — the header write already committed
        plan = FaultPlan(
            seed=11,
            rules=[FaultRule("storage.block.put", "die", after=1,
                             times=1)],
        )
        with active(plan):
            with pytest.raises(CollectorDied):
                ReplayDriver(bc, cfg).replay(chain)
        assert [s for (s, _, _, _) in plan.fired] == [
            "storage.block.put"
        ]
        # the torn record IS visible pre-recovery: header without body,
        # best never advanced (app_state moves only after a full save)
        assert bc.storages.app_state.best_block_number == 0
        assert bc.get_header_by_number(1) is not None
        assert bc.storages.block_body_storage.get(1) is None
        assert bc.storages.window_journal.pending()

        report = ReplayDriver(bc, cfg).recover()
        assert report.rolled_back >= 1
        assert report.best_after == 0
        assert bc.get_header_by_number(1) is None  # torn record undone
        assert bc.storages.window_journal.pending() == []

        ReplayDriver(bc, _cfg(window=1, depth=1)).replay(chain)
        _assert_same_chain(bc, _clean_reference(chain))


# --------------------- die in the collector during a regular_sync round


class TestRegularSyncCollectorDeath:
    """The windowed import path of a LIVE sync round (not a bare
    replay): the collector dies mid regular_sync import, the round
    fails locally without demoting the peer or killing the loop, and a
    restart-style recovery + resumed sync lands bit-exact."""

    @staticmethod
    def _loopback(server_bc, syncer_bc):
        from khipu_tpu.network.host_service import HostService
        from khipu_tpu.network.messages import Status
        from khipu_tpu.network.peer import PeerManager

        priv_a = (0xA11CE).to_bytes(32, "big")
        priv_b = (0xB0B).to_bytes(32, "big")

        def status_of(bc):
            def make():
                best = bc.best_block_number
                return Status(
                    protocol_version=63,
                    network_id=1,
                    total_difficulty=(
                        bc.get_total_difficulty(best) or 0
                    ),
                    best_hash=bc.get_hash_by_number(best),
                    genesis_hash=bc.get_hash_by_number(0),
                )
            return make

        server = PeerManager(
            priv_a, "khipu-tpu/server", status_of(server_bc)
        )
        HostService(server_bc).install(server)
        port = server.listen()
        client = PeerManager(
            priv_b, "khipu-tpu/client", status_of(syncer_bc)
        )
        client.connect(
            "127.0.0.1", port, privkey_to_pubkey(priv_a)
        )
        return server, client

    def test_collector_dies_mid_sync_round_then_recovery(self, chain):
        from khipu_tpu.sync.regular_sync import RegularSyncService

        serve_cfg = _cfg(window=1, depth=1)
        server_bc = _fresh(serve_cfg)
        ReplayDriver(server_bc, serve_cfg).replay(chain)

        cfg = _cfg(window=2, depth=2, degrade=False)
        syncer_bc = _fresh(cfg)
        server, client = self._loopback(server_bc, syncer_bc)
        try:
            sync = RegularSyncService(
                syncer_bc, cfg, client, batch_size=N_BLOCKS
            )
            # die right after the collector saves block 1: window [1,2]
            # is torn (1 on disk, 2 and the commit mark missing)
            plan = FaultPlan(
                seed=7,
                rules=[FaultRule("collector.save", "die", after=0,
                                 times=1)],
            )
            with active(plan):
                imported = sync.sync_once()
            # fail-stop semantics surface as a LOCAL round failure: the
            # loop survives, the peer is NOT blamed, nothing imported
            assert imported == 0
            assert [s for (s, _, _, _) in plan.fired] == [
                "collector.save"
            ]
            assert not client.blacklist.is_blacklisted(
                privkey_to_pubkey((0xA11CE).to_bytes(32, "big"))
            )
            # the torn window is on disk awaiting startup recovery
            assert syncer_bc.storages.app_state.best_block_number == 1
            assert syncer_bc.get_header_by_number(2) is None
            assert syncer_bc.storages.window_journal.pending()

            # "restart": recovery pass over the same storages, then a
            # fresh sync service (new driver, fresh collector)
            report = ReplayDriver(syncer_bc, cfg).recover()
            assert report.rolled_back >= 1
            assert report.best_after == 0
            assert syncer_bc.storages.window_journal.pending() == []

            resumed = RegularSyncService(
                syncer_bc, cfg, client, batch_size=N_BLOCKS
            )
            resumed.run(
                until=lambda: (
                    syncer_bc.best_block_number >= N_BLOCKS
                ),
                max_seconds=60,
            )
            _assert_same_chain(syncer_bc, _clean_reference(chain))
        finally:
            client.stop()
            server.stop()
