"""Conflict-aware scheduler tests (ledger/schedule.py, batch_exec.py,
sync/prefetch.py — ISSUE 14 execute-stage rebuild).

External oracles: the sequential fold (ChainBuilder builds every
fixture chain serially, so its headers ARE the serial roots/receipts/
gas), the optimistic-parallel path, and exact conflict-pair checks
re-derived from the documented footprint algebra — never from the
planner's own code.
"""

import dataclasses
import random

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import SyncConfig, fixture_config
from khipu_tpu.domain.account import EMPTY_CODE_HASH
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import (
    Transaction,
    contract_address,
    sign_transaction,
)
from khipu_tpu.ledger.schedule import (
    CALL,
    FAST,
    LEARNER,
    TemplateLearner,
    plan_block,
    reset_templates,
)
from khipu_tpu.ledger.world import (
    ON_ACCOUNT,
    ON_ADDRESS,
    ON_CODE,
    ON_STORAGE,
)
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.replay import ReplayDriver

CFG = fixture_config(chain_id=1)
NKEYS = 12
KEYS = [(i + 71).to_bytes(32, "big") for i in range(NKEYS)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
MINER = b"\xaa" * 20
GWEI = 10**9
ETH = 10**18
ALLOC = {a: 1000 * ETH for a in ADDRS}


def _cfg(parallel=True, scheduled=True):
    return dataclasses.replace(
        CFG, sync=SyncConfig(parallel_tx=parallel, scheduled_tx=scheduled)
    )


def _fresh(cfg, alloc=None):
    bc = Blockchain(Storages(), cfg)
    bc.load_genesis(GenesisSpec(alloc=alloc or ALLOC))
    return bc


def tx(i, nonce, to, value, gas=21_000, payload=b""):
    return sign_transaction(
        Transaction(nonce, GWEI, gas, to, value, payload),
        KEYS[i], chain_id=1,
    )


# --------------------------------------------------- plan disjointness


class _STX:
    """Planner-shaped stand-in: plan_block only reads ``.tx``."""

    def __init__(self, t):
        self.tx = t


def _conflicts(p, q):
    """The documented conflict relation, re-derived independently of
    the planner: read meets write/delta, write meets anything, slots
    intersect. D∩D and code∩code are NOT conflicts."""
    return bool(
        (p.acct_r & (q.acct_w | q.acct_d))
        or (q.acct_r & (p.acct_w | p.acct_d))
        or (p.acct_w & (q.acct_r | q.acct_w | q.acct_d))
        or (q.acct_w & (p.acct_r | p.acct_w | p.acct_d))
        or (p.slots & q.slots)
    )


class TestPlanDisjointness:
    def _random_block(self, rng, learner, token, token_hash):
        """A planner-hostile tx mix: few senders (hot chains), shared
        recipients, coinbase touches, creations, precompile targets,
        zero-value transfers, and template calls to ``token``."""
        pool = ADDRS[:6]
        txs, senders = [], []
        for j in range(rng.randrange(8, 30)):
            sender = rng.choice(pool)
            r = rng.random()
            if r < 0.05:
                t = Transaction(j, GWEI, 53_000, None, 0, b"\x00")
            elif r < 0.10:
                t = Transaction(j, GWEI, 21_000, MINER, 5)
            elif r < 0.15:
                t = Transaction(
                    j, GWEI, 21_000, (0x07).to_bytes(20, "big"), 5
                )
            elif r < 0.25:
                t = Transaction(j, GWEI, 21_000, rng.choice(pool), 0)
            elif r < 0.55:
                payload = rng.randrange(1, 9).to_bytes(32, "big")
                t = Transaction(j, GWEI, 90_000, token, 0, payload)
            else:
                t = Transaction(
                    j, GWEI, 21_000,
                    rng.choice(pool + ADDRS[6:10]), rng.randrange(1, 99),
                )
            txs.append(_STX(t))
            senders.append(sender)
        return txs, senders

    def test_batches_pairwise_disjoint_over_seeds(self):
        """Property: within every planned batch, all predicted pairs
        are conflict-free under the independently-derived relation,
        residues are singleton barriers, and the plan is a permutation
        of the block."""
        token = b"\x70" * 20
        token_hash = b"\x71" * 32
        learner = TemplateLearner()
        # teach one template (balance[arg0]-style) via the public API
        learner.observe(
            token_hash, ADDRS[0], token,
            (5).to_bytes(32, "big"),
            reads={ON_ACCOUNT: {ADDRS[0], token}, ON_ADDRESS: set(),
                   ON_STORAGE: {(token, 5)}, ON_CODE: {token}},
            written={ON_ACCOUNT: {ADDRS[0]}, ON_ADDRESS: set(),
                     ON_STORAGE: {(token, 5)}, ON_CODE: set()},
        )

        def code_hash_of(addr):
            return token_hash if addr == token else EMPTY_CODE_HASH

        for seed in range(40):
            rng = random.Random(seed)
            txs, senders = self._random_block(
                rng, learner, token, token_hash
            )
            plan = plan_block(txs, senders, MINER, code_hash_of, learner)
            seen = []
            for step in plan.steps:
                seen.extend(step.indices)
                if step.kind == "residue":
                    assert len(step.indices) == 1
                    assert step.indices[0] not in plan.predicted
                    continue
                assert step.indices == sorted(step.indices)
                preds = [plan.predicted[i] for i in step.indices]
                for a in range(len(preds)):
                    for b in range(a + 1, len(preds)):
                        assert not _conflicts(preds[a], preds[b]), (
                            f"seed {seed}: batch {step.indices} txs "
                            f"{step.indices[a]},{step.indices[b]} conflict"
                        )
            assert sorted(seen) == list(range(len(txs))), (
                f"seed {seed}: plan is not a permutation of the block"
            )
            assert plan.n_fast + plan.n_call + plan.n_residue == len(txs)

    def test_conflicting_pairs_keep_index_order(self):
        """Two transfers from ONE sender must land in increasing
        batches (read-of-sender meets delta-on-sender)."""
        txs = [
            _STX(Transaction(0, GWEI, 21_000, ADDRS[5], 1)),
            _STX(Transaction(1, GWEI, 21_000, ADDRS[6], 1)),
        ]
        plan = plan_block(
            txs, [ADDRS[0], ADDRS[0]], MINER,
            lambda a: EMPTY_CODE_HASH, TemplateLearner(),
        )
        batch_of = {}
        for pos, step in enumerate(plan.steps):
            for i in step.indices:
                batch_of[i] = pos
        assert batch_of[0] < batch_of[1]
        assert plan.conflicted == 1

    def test_pure_credit_overlap_shares_a_batch(self):
        """Two different senders paying the SAME recipient commute
        (D∩D) and must share the widest batch."""
        txs = [
            _STX(Transaction(0, GWEI, 21_000, ADDRS[7], 1)),
            _STX(Transaction(0, GWEI, 21_000, ADDRS[7], 2)),
        ]
        plan = plan_block(
            txs, [ADDRS[0], ADDRS[1]], MINER,
            lambda a: EMPTY_CODE_HASH, TemplateLearner(),
        )
        assert plan.max_width == 2 and plan.conflicted == 0


# ------------------------------------------------- 120-seed oracle sweep


# the conflict-storm token from the contended bench: writes
# balance[CALLER] and balance[arg0] — learnable as ("caller",)/("arg",0)
_TOKEN_RUNTIME = bytes([
    0x60, 0x00, 0x35, 0x60, 0x20, 0x35, 0x33, 0x54, 0x81, 0x90, 0x03,
    0x33, 0x55, 0x81, 0x54, 0x01, 0x90, 0x55, 0x00,
])


def _init_code(runtime):
    return (
        bytes([0x60 + len(runtime) - 1]) + runtime
        + bytes([0x60, 0x00, 0x52])
        + bytes([0x60, len(runtime), 0x60, 32 - len(runtime), 0xF3])
    )


class TestScheduledOracleSweep:
    def _random_chain(self, seed, n_tx_blocks=4, txs_per_block=12):
        """Deploy the token, then ``n_tx_blocks`` blocks of a seeded
        adversarial tx mix: transfers (hot + disjoint), template calls,
        zero-value touches, coinbase payments, creations. Multi-block
        on purpose (ISSUE 17): the token calls must live long enough to
        cross TRUST_AFTER confirmations so the later blocks' calls run
        through the TRUSTED vectorized batch lane, not just checked."""
        rng = random.Random(seed)
        cfg = _cfg(parallel=False)
        builder = ChainBuilder(
            Blockchain(Storages(), cfg), cfg, GenesisSpec(alloc=ALLOC)
        )
        token = contract_address(ADDRS[0], 0)
        blocks = [builder.add_block(
            [tx(0, 0, None, 0, gas=500_000,
                payload=_init_code(_TOKEN_RUNTIME))],
            coinbase=MINER,
        )]
        nonces = [1] + [0] * (NKEYS - 1)
        for _ in range(n_tx_blocks):
            txs = []
            for _ in range(txs_per_block):
                i = rng.randrange(NKEYS)
                r = rng.random()
                if r < 0.30:
                    # hot transfers: few recipients, frequent sender
                    # reuse
                    txs.append(tx(i, nonces[i], rng.choice(ADDRS[:4]),
                                  1 + rng.randrange(50)))
                elif r < 0.55:
                    payload = (
                        ADDRS[rng.randrange(NKEYS)].rjust(32, b"\x00")
                        + (1 + rng.randrange(3)).to_bytes(32, "big")
                    )
                    txs.append(tx(i, nonces[i], token, 0, gas=200_000,
                                  payload=payload))
                elif r < 0.65:
                    txs.append(tx(i, nonces[i], rng.choice(ADDRS), 0,
                                  gas=30_000))
                elif r < 0.72:
                    txs.append(tx(i, nonces[i], MINER, 7))
                elif r < 0.78:
                    txs.append(tx(i, nonces[i], None, 0, gas=60_000,
                                  payload=b"\x00"))
                else:
                    txs.append(tx(
                        i, nonces[i],
                        bytes.fromhex(
                            "%040x" % (0xE0000000 + rng.randrange(8))),
                        1 + rng.randrange(9),
                    ))
                nonces[i] += 1
            blocks.append(builder.add_block(txs, coinbase=MINER))
        return blocks

    @pytest.mark.parametrize("bank", range(4))
    def test_scheduled_bit_exact_vs_serial_and_optimistic(self, bank):
        """120 seeds (4 banks x 30): the scheduled path must land on
        the EXACT chain the serial fold built (roots + receipts root +
        gas all live in the sealed header; the replay validates
        against it and raises on any divergence), and so must the
        optimistic path. Templates reset between seeds — every seed
        re-learns from its own residue, and the 4-block chains carry
        the token past TRUST_AFTER so the trusted vectorized call lane
        executes real traffic inside the sweep."""
        from khipu_tpu.ledger.schedule import EXEC_GAUGES

        total_fast = total_residue = 0
        vector_before = EXEC_GAUGES["vector_call_txs"]
        for seed in range(bank * 30, bank * 30 + 30):
            blocks = self._random_chain(seed)
            reset_templates()
            for cfg in (_cfg(scheduled=True), _cfg(scheduled=False)):
                bc = _fresh(cfg)
                stats = ReplayDriver(bc, cfg).replay(blocks)
                assert (
                    bc.get_header_by_number(len(blocks)).hash
                    == blocks[-1].hash
                ), f"seed {seed} diverged (scheduled="\
                   f"{cfg.sync.scheduled_tx})"
                if cfg.sync.scheduled_tx:
                    total_fast += stats.fast_path_txs
                    total_residue += stats.residue_txs
        # the sweep must actually exercise both executors AND the
        # trusted templated-call lane (not just checked calls)
        assert total_fast > 0 and total_residue > 0
        assert EXEC_GAUGES["vector_call_txs"] > vector_before

    def test_template_call_batches_after_learning(self):
        """Same-shaped token calls: the first call runs residue (and
        teaches the learner), a later block's call is CALL-predicted —
        the learner's effect is visible in the stats, not just gauges.
        Blocks carry >=2 txs (single-tx blocks take the sequential
        path) and are BUILT serially, so all learning happens in the
        replay under test."""
        cfg = _cfg()
        seq = _cfg(parallel=False)
        builder = ChainBuilder(
            Blockchain(Storages(), seq), seq, GenesisSpec(alloc=ALLOC)
        )
        token = contract_address(ADDRS[0], 0)
        payload = ADDRS[9].rjust(32, b"\x00") + (1).to_bytes(32, "big")
        blocks = [
            builder.add_block(
                [tx(0, 0, None, 0, gas=500_000,
                    payload=_init_code(_TOKEN_RUNTIME)),
                 tx(4, 0, ADDRS[10], 3)],
                coinbase=MINER,
            ),
            builder.add_block(
                [tx(1, 0, token, 0, gas=200_000, payload=payload),
                 tx(5, 0, ADDRS[10], 3)],
                coinbase=MINER,
            ),
            builder.add_block(
                [tx(2, 0, token, 0, gas=200_000, payload=payload),
                 tx(3, 0, ADDRS[8], 5)],
                coinbase=MINER,
            ),
        ]
        reset_templates()
        bc = _fresh(cfg)
        stats = ReplayDriver(bc, cfg).replay(blocks)
        assert bc.get_header_by_number(3).hash == blocks[-1].hash
        # block 2's call learned the template; block 3's call took the
        # scheduled CALL lane (parallel) instead of the residue
        assert stats.residue_txs == 2  # deploy + learning call
        assert stats.fast_path_txs == 3  # the plain transfers
        assert stats.parallel_txs == 4  # transfers + template call
        code_hash = bc.get_world_state(
            blocks[0].header.state_root
        ).get_code_hash(token)
        verdict = LEARNER.lookup(code_hash)
        assert verdict is not None and verdict != "opaque"
        assert ("caller",) in verdict.rules and ("arg", 0) in verdict.rules


# --------------------------------------------------- misprediction path


class TestMispredictionFallback:
    # SSTORE(arg0 XOR arg1, 1): with arg1=0 the learner derives
    # ("arg", 0); a later call with arg1 != 0 lands on a DIFFERENT
    # slot than predicted -> footprint check fails -> whole-block
    # fallback to the optimistic oracle
    XOR_RUNTIME = bytes([
        0x60, 0x01, 0x60, 0x00, 0x35, 0x60, 0x20, 0x35, 0x18, 0x55,
        0x00,
    ])

    def test_misprediction_falls_back_bit_exact(self):
        cfg = _cfg()
        seq = _cfg(parallel=False)
        builder = ChainBuilder(
            Blockchain(Storages(), seq), seq, GenesisSpec(alloc=ALLOC)
        )
        xor = contract_address(ADDRS[0], 0)

        def call(i, nonce, a0, a1):
            return tx(
                i, nonce, xor, 0, gas=100_000,
                payload=a0.to_bytes(32, "big") + a1.to_bytes(32, "big"),
            )

        blocks = [
            builder.add_block(
                [tx(0, 0, None, 0, gas=500_000,
                    payload=_init_code(self.XOR_RUNTIME)),
                 tx(4, 0, ADDRS[10], 3)],
                coinbase=MINER,
            ),
            # learning call: arg1=0 -> slot == arg0 -> ("arg", 0)
            builder.add_block(
                [call(1, 0, 5, 0), tx(5, 0, ADDRS[10], 3)],
                coinbase=MINER,
            ),
            # poisoned call: slot is 5^7=2, prediction says 5
            builder.add_block(
                [call(2, 0, 5, 7), tx(3, 0, ADDRS[8], 9)],
                coinbase=MINER,
            ),
        ]
        reset_templates()
        bc = _fresh(cfg)
        stats = ReplayDriver(bc, cfg).replay(blocks)
        # correctness never depended on the prediction
        assert bc.get_header_by_number(3).hash == blocks[-1].hash
        assert stats.mispredictions >= 1
        # the poisoned code hash is demoted: re-running the same chain
        # routes its calls straight to the residue, no second fallback
        code_hash = bc.get_world_state(
            blocks[0].header.state_root
        ).get_code_hash(xor)
        assert LEARNER.lookup(code_hash) == "opaque"
        bc2 = _fresh(cfg)
        stats2 = ReplayDriver(bc2, cfg).replay(blocks)
        assert bc2.get_header_by_number(3).hash == blocks[-1].hash
        assert stats2.mispredictions == 0


# ------------------------------------------- mapping-slot templates


# ERC-20 transfer(to, amount) with real keccak mapping slots: balances
# at keccak(pad32(holder) ++ pad32(0)); calldata is the raw two words
# (arg0 = recipient, arg1 = amount). Straight-line + whitelisted, so
# the purity scan passes and the learner can trust it after
# confirmation (ISSUE 17)
_ERC20_RUNTIME = bytes([
    0x33, 0x60, 0x00, 0x52,              # mem[0:32] = caller
    0x60, 0x00, 0x60, 0x20, 0x52,        # mem[32:64] = 0 (base slot)
    0x60, 0x40, 0x60, 0x00, 0x20,        # sender slot = SHA3(0, 64)
    0x80, 0x54,                          # sender balance
    0x60, 0x20, 0x35, 0x90, 0x03,        # bal - amount
    0x90, 0x55,                          # debit sender
    0x60, 0x00, 0x35, 0x60, 0x00, 0x52,  # mem[0:32] = recipient
    0x60, 0x40, 0x60, 0x00, 0x20,        # recipient slot = SHA3(0, 64)
    0x80, 0x54,                          # recipient balance
    0x60, 0x20, 0x35, 0x01,              # bal + amount
    0x90, 0x55,                          # credit recipient
    0x00,                                # STOP
])


def _codecopy_init(runtime):
    """Constructor for runtimes wider than one PUSH word."""
    return bytes([
        0x60, len(runtime), 0x60, 0x0C, 0x60, 0x00, 0x39,  # CODECOPY
        0x60, len(runtime), 0x60, 0x00, 0xF3,              # RETURN
    ]) + runtime


class TestMappingTemplates:
    def _erc20_chain(self, n_call_blocks):
        """Deploy the ERC-20, then ``n_call_blocks`` blocks of two
        disjoint transfer(to, amount) calls each plus a filler
        transfer (single-tx blocks take the sequential path and would
        teach nothing)."""
        seq = _cfg(parallel=False)
        builder = ChainBuilder(
            Blockchain(Storages(), seq), seq, GenesisSpec(alloc=ALLOC)
        )
        token = contract_address(ADDRS[0], 0)

        def call(i, nonce, rcpt, amount):
            return tx(
                i, nonce, token, 0, gas=200_000,
                payload=rcpt.rjust(32, b"\x00")
                + amount.to_bytes(32, "big"),
            )

        blocks = [builder.add_block(
            [tx(0, 0, None, 0, gas=500_000,
                payload=_codecopy_init(_ERC20_RUNTIME)),
             tx(4, 0, ADDRS[10], 3)],
            coinbase=MINER,
        )]
        nonces = [1] + [0] * (NKEYS - 1)
        nonces[4] = 1
        holders = [
            bytes.fromhex("%040x" % (0xE20E2000 + i)) for i in range(8)
        ]
        for n in range(n_call_blocks):
            s1, s2, filler = 1 + (n % 3), 5 + (n % 3), 8 + (n % 4)
            txs = [
                call(s1, nonces[s1], holders[n % 8], 100 + 7 * n),
                call(s2, nonces[s2], holders[(n + 3) % 8], 5 + n),
                tx(filler, nonces[filler], ADDRS[11], 2 + n),
            ]
            for i in (s1, s2, filler):
                nonces[i] += 1
            blocks.append(builder.add_block(txs, coinbase=MINER))
        return blocks, token

    def test_mapping_rules_promote_after_one_observation(self):
        """One observed call is enough to derive BOTH mapping-form
        write rules — debit keccak(caller || 0), credit
        keccak(arg0 || 0) — with the arg-delta effect shapes. No
        second observation, no confirmation required for the template
        (trust comes later; the template itself must exist now)."""
        from khipu_tpu.ledger.schedule import TRUST_AFTER

        blocks, token = self._erc20_chain(1)
        reset_templates()
        cfg = _cfg()
        bc = _fresh(cfg)
        ReplayDriver(bc, cfg).replay(blocks)
        assert bc.get_header_by_number(len(blocks)).hash == blocks[-1].hash
        code_hash = bc.get_world_state(
            blocks[0].header.state_root
        ).get_code_hash(token)
        verdict = LEARNER.lookup(code_hash)
        assert verdict is not None and verdict != "opaque"
        assert ("map_caller", 0) in verdict.rules
        assert ("map_arg", 0, 0) in verdict.rules
        assert ("map_caller", 0) in verdict.write_rules
        assert ("map_arg", 0, 0) in verdict.write_rules
        # the purity scan accepted the runtime, but one observation is
        # NOT trust: effects only exist after checked confirmations,
        # and the vectorized lane further needs TRUST_AFTER of them
        assert verdict.scan is not None
        assert verdict.effects is None
        assert verdict.confirmations < TRUST_AFTER

    def test_trusted_mapping_calls_run_vectorized_bit_exact(self):
        """Past TRUST_AFTER checked confirmations the mapping calls
        execute in the trusted vectorized batch lane — visible in the
        vector_call_txs gauge — and the replay still lands on the
        serial fold's exact headers."""
        from khipu_tpu.ledger.schedule import EXEC_GAUGES, TRUST_AFTER

        blocks, token = self._erc20_chain(6)
        reset_templates()
        cfg = _cfg()
        bc = _fresh(cfg)
        before = EXEC_GAUGES["vector_call_txs"]
        stats = ReplayDriver(bc, cfg).replay(blocks)
        assert bc.get_header_by_number(len(blocks)).hash == blocks[-1].hash
        assert stats.mispredictions == 0
        # blocks 2..1+TRUST_AFTER run checked; the remaining call
        # blocks (2 calls each) run trusted
        expect_vector = 2 * (6 - 1 - TRUST_AFTER)
        assert EXEC_GAUGES["vector_call_txs"] - before >= expect_vector
        code_hash = bc.get_world_state(
            blocks[0].header.state_root
        ).get_code_hash(token)
        verdict = LEARNER.lookup(code_hash)
        assert verdict.confirmations >= TRUST_AFTER
        assert verdict.vectorizable
        # learned effects: debit is old - arg1, credit is old + arg1
        by_rule = dict(zip(verdict.write_rules, verdict.effects))
        assert by_rule[("map_caller", 0)][0] == ("old_sub_arg", 1)
        assert by_rule[("map_arg", 0, 0)][0] == ("old_add_arg", 1)

    # poisoned mapping: SSTORE(keccak(pad32(caller) ++ pad32(arg1)),
    # arg0) — with arg1=0 the learner derives ("map_caller", 0); a
    # later call with arg1 != 0 writes a DIFFERENT mapping bucket than
    # predicted -> footprint escape -> fallback + permanent demotion
    POISON_RUNTIME = bytes([
        0x33, 0x60, 0x00, 0x52,        # mem[0:32] = caller
        0x60, 0x20, 0x35,              # arg1 (base slot, attacker's)
        0x60, 0x20, 0x52,              # mem[32:64] = arg1
        0x60, 0x40, 0x60, 0x00, 0x20,  # slot = SHA3(0, 64)
        0x60, 0x00, 0x35,              # arg0 (value)
        0x90, 0x55,                    # SSTORE(slot, arg0)
        0x00,
    ])

    def test_poisoned_mapping_slot_demotes_bit_exact(self):
        """The mapping analog of the XOR misprediction test: the
        derived ("map_caller", 0) rule is a lie the learner cannot see
        from one observation. The poisoned call must fall back
        whole-block (bit-exact), demote the code hash to opaque, and a
        re-run must take the residue path with no second fallback."""
        cfg = _cfg()
        seq = _cfg(parallel=False)
        builder = ChainBuilder(
            Blockchain(Storages(), seq), seq, GenesisSpec(alloc=ALLOC)
        )
        poison = contract_address(ADDRS[0], 0)

        def call(i, nonce, a0, a1):
            return tx(
                i, nonce, poison, 0, gas=100_000,
                payload=a0.to_bytes(32, "big") + a1.to_bytes(32, "big"),
            )

        blocks = [
            builder.add_block(
                [tx(0, 0, None, 0, gas=500_000,
                    payload=_init_code(self.POISON_RUNTIME)),
                 tx(4, 0, ADDRS[10], 3)],
                coinbase=MINER,
            ),
            # learning call: arg1=0 -> slot == keccak(caller || 0)
            builder.add_block(
                [call(1, 0, 0x99, 0), tx(5, 0, ADDRS[10], 3)],
                coinbase=MINER,
            ),
            # poisoned call: arg1=3 writes keccak(caller || 3), the
            # prediction says keccak(caller || 0)
            builder.add_block(
                [call(2, 0, 7, 3), tx(3, 0, ADDRS[8], 9)],
                coinbase=MINER,
            ),
        ]
        reset_templates()
        bc = _fresh(cfg)
        stats = ReplayDriver(bc, cfg).replay(blocks)
        assert bc.get_header_by_number(3).hash == blocks[-1].hash
        assert stats.mispredictions >= 1
        code_hash = bc.get_world_state(
            blocks[0].header.state_root
        ).get_code_hash(poison)
        assert LEARNER.lookup(code_hash) == "opaque"
        bc2 = _fresh(cfg)
        stats2 = ReplayDriver(bc2, cfg).replay(blocks)
        assert bc2.get_header_by_number(3).hash == blocks[-1].hash
        assert stats2.mispredictions == 0

    def test_demotion_is_permanent(self):
        """Opaque is forever: once demoted, no stream of perfectly
        consistent observations may resurrect the template — the
        promote/demote protocol must not oscillate."""
        from khipu_tpu.native.keccak import keccak256_batch

        token = b"\x70" * 20
        code_hash = b"\x73" * 32
        learner = TemplateLearner()
        sender = ADDRS[1]
        slot = int.from_bytes(keccak256_batch(
            [sender.rjust(32, b"\x00") + b"\x00" * 32]
        )[0], "big")
        footprint = dict(
            reads={ON_ACCOUNT: {sender, token}, ON_ADDRESS: set(),
                   ON_STORAGE: {(token, slot)}, ON_CODE: {token}},
            written={ON_ACCOUNT: {sender}, ON_ADDRESS: set(),
                     ON_STORAGE: {(token, slot)}, ON_CODE: set()},
        )
        payload = (5).to_bytes(32, "big")
        learner.observe(code_hash, sender, token, payload, **footprint)
        assert learner.lookup(code_hash) != "opaque"
        learner.demote(code_hash)
        assert learner.lookup(code_hash) == "opaque"
        for _ in range(5):
            learner.observe(code_hash, sender, token, payload,
                            **footprint)
            assert learner.lookup(code_hash) == "opaque"

    def test_concurrent_observation_determinism(self):
        """Racing observers must converge on the SAME template a
        serial pass derives, for every interleaving — the learner is
        shared across executor threads and a rule set that depended on
        arrival order would make replay nondeterministic."""
        import threading

        from khipu_tpu.native.keccak import keccak256_batch

        token = b"\x70" * 20
        code_hash = b"\x74" * 32

        def observation(i):
            sender = ADDRS[i]
            rcpt = ADDRS[(i + 5) % NKEYS]
            amount = 3 + i
            pre = [sender.rjust(32, b"\x00") + b"\x00" * 32,
                   rcpt.rjust(32, b"\x00") + b"\x00" * 32]
            ss, rs = [
                int.from_bytes(k, "big") for k in keccak256_batch(pre)
            ]
            payload = (rcpt.rjust(32, b"\x00")
                       + amount.to_bytes(32, "big"))
            return sender, payload, dict(
                reads={ON_ACCOUNT: {sender, token}, ON_ADDRESS: set(),
                       ON_STORAGE: {(token, ss), (token, rs)},
                       ON_CODE: {token}},
                written={ON_ACCOUNT: {sender}, ON_ADDRESS: set(),
                         ON_STORAGE: {(token, ss), (token, rs)},
                         ON_CODE: set()},
            )

        obs = [observation(i) for i in range(NKEYS)]
        serial = TemplateLearner()
        for sender, payload, fp in obs:
            serial.observe(code_hash, sender, token, payload, **fp)
        ref = serial.lookup(code_hash)
        assert ref != "opaque" and ("map_caller", 0) in ref.rules
        for trial in range(8):
            rng = random.Random(trial)
            learner = TemplateLearner()
            order = list(obs)
            rng.shuffle(order)
            threads = [
                threading.Thread(
                    target=lambda o=o: learner.observe(
                        code_hash, o[0], token, o[1], **o[2]
                    )
                )
                for o in order
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            got = learner.lookup(code_hash)
            assert got != "opaque", f"trial {trial} went opaque"
            assert got.rules == ref.rules, f"trial {trial} diverged"
            assert got.write_rules == ref.write_rules


# ------------------------------------------------ sender prefetch cache


class TestSenderPrefetch:
    def _wire_blocks(self, n_blocks=3, txs_per_block=4):
        from khipu_tpu.domain.block import Block

        cfg = _cfg(parallel=False)
        builder = ChainBuilder(
            Blockchain(Storages(), cfg), cfg, GenesisSpec(alloc=ALLOC)
        )
        nonces = [0] * NKEYS
        blocks = []
        for n in range(n_blocks):
            txs = []
            for j in range(txs_per_block):
                i = (n * txs_per_block + j) % NKEYS
                txs.append(tx(i, nonces[i], ADDRS[(i + 5) % NKEYS], 1 + n))
                nonces[i] += 1
            blocks.append(builder.add_block(txs, coinbase=MINER))
        # wire round-trip: decode drops every per-object sender memo
        return [Block.decode(b.encode()) for b in blocks]

    def test_cache_hit_on_reimport(self):
        from khipu_tpu.sync.prefetch import (
            PREFETCH_GAUGES,
            flush_sender_cache,
            recover_block_senders,
            sender_cache_len,
        )

        flush_sender_cache()
        blocks = self._wire_blocks(n_blocks=1)
        stxs = blocks[0].body.transactions
        h0, m0 = PREFETCH_GAUGES["hits"], PREFETCH_GAUGES["misses"]
        recover_block_senders(stxs)
        assert PREFETCH_GAUGES["misses"] == m0 + len(stxs)
        assert PREFETCH_GAUGES["hits"] == h0
        first = [s.sender for s in stxs]
        assert all(a in ADDRS for a in first)
        assert sender_cache_len() == len(stxs)

        # the re-import: fresh decode, same wire bytes — all hits
        from khipu_tpu.domain.block import Block

        again = Block.decode(blocks[0].encode()).body.transactions
        assert all("sender" not in s.__dict__ for s in again)
        recover_block_senders(again)
        assert PREFETCH_GAUGES["hits"] == h0 + len(stxs)
        assert PREFETCH_GAUGES["misses"] == m0 + len(stxs)
        assert [s.sender for s in again] == first
        flush_sender_cache()
        assert sender_cache_len() == 0

    def test_lru_eviction_bounds_the_cache(self):
        from khipu_tpu.sync.prefetch import (
            PREFETCH_GAUGES,
            flush_sender_cache,
            recover_block_senders,
            sender_cache_len,
        )

        flush_sender_cache()
        blocks = self._wire_blocks(n_blocks=1, txs_per_block=6)
        e0 = PREFETCH_GAUGES["evictions"]
        recover_block_senders(
            blocks[0].body.transactions, cache_entries=2
        )
        assert sender_cache_len() == 2
        assert PREFETCH_GAUGES["evictions"] == e0 + 4
        flush_sender_cache()

    def test_prefetcher_fills_memos_in_order(self):
        from khipu_tpu.sync.prefetch import SenderPrefetcher

        blocks = self._wire_blocks()
        pf = SenderPrefetcher(blocks, depth=2)
        out = list(pf)
        pf.close()  # idempotent after natural drain
        assert [b.header.number for b in out] == [
            b.header.number for b in blocks
        ]
        for b in out:
            assert all(
                "sender" in s.__dict__ for s in b.body.transactions
            )

    def test_prefetcher_propagates_source_errors_in_position(self):
        from khipu_tpu.sync.prefetch import SenderPrefetcher

        blocks = self._wire_blocks()

        def source():
            yield blocks[0]
            raise RuntimeError("wire hiccup")

        pf = SenderPrefetcher(source(), depth=2)
        it = iter(pf)
        assert next(it).header.number == blocks[0].header.number
        with pytest.raises(RuntimeError, match="wire hiccup"):
            next(it)
        pf.close()


# --------------------------------------------------- process-wide pool


class TestExecPool:
    def test_pool_is_shared_and_resizable(self):
        from khipu_tpu.ledger.ledger import _exec_pool, shutdown_exec_pool

        a = _exec_pool(4)
        assert _exec_pool(4) is a  # same width -> same pool
        b = _exec_pool(2)
        assert b is not a  # width change rebuilds
        assert _exec_pool(2) is b
        shutdown_exec_pool()
        c = _exec_pool(2)
        assert c is not b  # shutdown releases; next call rebuilds
        assert c.submit(lambda: 41 + 1).result() == 42
        shutdown_exec_pool()
