"""Gameday harness (khipu_tpu/chaos/scenario.py, invariants.py, the
merge/extend composition layer in chaos/plan.py — docs/gameday.md).

The headline: a pairwise hazard matrix — every ordered pair of hazard
kinds x seeds, 120 composed runs over the windowed replay pipeline —
where every run recovers to a BIT-EXACT chain and the sweep genuinely
exercises both outcomes (killed > 20 AND survived > 20), with the
schedule and the fired-fault log deterministic under one seed. Plus
the composition primitives that make it sound: ``merge_plans``
preserves per-(rule, site) RNG independence (merged schedule == union
of the parts'), the scenario engine fires milestone-keyed events
exactly once in order, watchdog trips carry the scenario event id as
a ``scenario`` label, every chaos seam in the tree is registered AND
exercised (meta-test), and the named reorg-during-rebalance
regression: a fork battle fencing the primary mid-stream must not
perturb the epoch fence — the ring lands at exactly the old or the
new epoch.
"""

import ast
import dataclasses
import threading
from pathlib import Path

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.chaos import (
    KNOWN_SEAMS,
    FaultPlan,
    FaultRule,
    InjectedDeath,
    InjectedFault,
    InvariantReport,
    InvariantResult,
    Scenario,
    ScenarioEngine,
    ScenarioEvent,
    active,
    check_epoch,
    check_roots_bit_exact,
    clear_current_event,
    current_event_id,
    derive,
    gameday_stats,
    known_seam,
    merge_plans,
    quiet_deaths,
    record_run,
)
from khipu_tpu.cluster import Rebalancer, ShardedNodeClient
from khipu_tpu.cluster.ring import _point
from khipu_tpu.config import SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.observability.registry import MetricsRegistry
from khipu_tpu.observability.telemetry import TelemetryConfig, Watchdog
from khipu_tpu.storage.datasource import (
    MemoryBlockDataSource,
    MemoryKeyValueDataSource,
    MemoryNodeDataSource,
)
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.reorg import ReorgManager
from khipu_tpu.sync.replay import CollectorDied, ReplayDriver, ReplayStats

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _no_sticky_scenario():
    """current_event_id() is sticky by design (the watchdog may trip
    after the hazard); don't let it leak into other test modules'
    watchdog assertions."""
    yield
    clear_current_event()


REPO = Path(__file__).resolve().parents[1]
CFG = dataclasses.replace(
    fixture_config(chain_id=1),
    sync=SyncConfig(commit_window_blocks=1, parallel_tx=False),
)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(4)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18
ALLOC = {a: 1000 * ETH for a in ADDRS}
GEN = GenesisSpec(alloc=ALLOC)
MINER_A = b"\xaa" * 20
MINER_B = b"\xbb" * 20
N_BLOCKS = 12

_noop = lambda s: None  # noqa: E731 - plan sleep stub


def _tx(i, nonce, to, value):
    return sign_transaction(
        Transaction(nonce, 10**9, 21_000, to, value), KEYS[i], chain_id=1
    )


def _build(n, diverge_at=None, value_off=0):
    """Consensus-true transfer chain; from ``diverge_at`` the coinbase
    and tx values flip (test_reorg's fork-building idiom), so the
    suffix is a genuinely different branch."""
    builder = ChainBuilder(Blockchain(Storages(), CFG), CFG, GEN)
    blocks, nonces = [], [0, 0, 0, 0]
    for k in range(n):
        i = k % 4
        diverged = diverge_at is not None and k >= diverge_at
        blocks.append(builder.add_block(
            [_tx(i, nonces[i], ADDRS[(i + 1) % 4],
                 100 + k + (value_off if diverged else 0))],
            coinbase=MINER_B if diverged else MINER_A,
            timestamp=10 * (k + 1),
        ))
        nonces[i] += 1
    return builder.blockchain, blocks


@pytest.fixture(scope="module")
def chain():
    """12 transfer blocks for the matrix — enough window boundaries
    for a depth-2 pipeline to be mid-flight whenever a hazard lands."""
    return _build(N_BLOCKS)[1]


@pytest.fixture(scope="module")
def reference(chain):
    """Uninterrupted serial replay — the bit-exact oracle."""
    bc = _fresh(CFG)
    ReplayDriver(bc, CFG).replay(chain)
    return bc


@pytest.fixture(scope="module")
def fork_chains():
    """(base 8, fork 10 diverging at 5) for the reorg regression."""
    _, base = _build(8)
    fork_bc, fork = _build(10, diverge_at=5, value_off=1000)
    return {"base": base, "fork": fork, "fork_bc": fork_bc}


def _fresh(cfg):
    bc = Blockchain(Storages(), cfg)
    bc.load_genesis(GEN)
    return bc


def _windowed_cfg():
    # adaptive_commit off so the collector seams sit on the configured
    # path (the test_chaos sweep convention); degrade off so a stage
    # death surfaces as CollectorDied and the run is counted "killed"
    return dataclasses.replace(
        CFG,
        sync=SyncConfig(
            parallel_tx=False,
            commit_window_blocks=2,
            pipeline_depth=2,
            degrade_on_collector_death=False,
            collector_join_timeout=5.0,
            adaptive_commit=False,
        ),
    )


# --------------------------------------------------------- merge_plans


class TestMergePlans:
    """Satellite: composition preserves per-(rule, site) RNG
    independence — the property the gameday's single shared plan
    stands on."""

    @staticmethod
    def _drive(plan):
        for i in range(300):
            plan.fire("storage.kv.get")
            plan.fire("kesque.append" if i % 3 else "kesque.roll")
        return {(s, h, k) for (s, h, k, _i) in plan.fired}

    @staticmethod
    def _part_a():
        return FaultPlan(seed=7, rules=[
            FaultRule("storage.kv.get", "latency", prob=0.31,
                      latency_s=0.0),
            FaultRule("kesque.*", "latency", prob=0.2, latency_s=0.0),
        ], sleep=_noop)

    @staticmethod
    def _part_b():
        return FaultPlan(seed=9, rules=[
            FaultRule("storage.kv.get", "latency", prob=0.4,
                      latency_s=0.0),
        ], sleep=_noop)

    def test_merged_schedule_is_union_of_parts(self):
        union = self._drive(self._part_a()) | self._drive(self._part_b())
        merged = merge_plans(self._part_a(), self._part_b())
        assert self._drive(merged) == union
        # and both parts genuinely contributed
        assert self._drive(self._part_a()) < union

    def test_naive_concat_aliases_the_second_plans_streams(self):
        """The bug merge_plans exists to fix: concatenating rules under
        one seed re-keys part B's RNG streams, silently changing which
        hits B fires on."""
        union = self._drive(self._part_a()) | self._drive(self._part_b())
        naive = FaultPlan(
            seed=7,
            rules=list(self._part_a().rules) + list(self._part_b().rules),
            sleep=_noop,
        )
        assert self._drive(naive) != union

    def test_extend_draws_identically_to_upfront_construction(self):
        rules = [
            FaultRule("storage.kv.get", "latency", prob=0.3,
                      latency_s=0.0),
            FaultRule("kesque.append", "latency", prob=0.5,
                      latency_s=0.0),
        ]
        up = FaultPlan(seed=5, rules=list(rules), sleep=_noop)
        ex = FaultPlan(seed=5, rules=rules[:1], sleep=_noop)
        ex.extend(rules[1:])
        self._drive(up)
        self._drive(ex)
        assert up.fired == ex.fired

    def test_merged_plan_extends_under_first_parts_key_sequence(self):
        """Rules armed onto a merged plan (what the scenario engine
        does mid-run) draw exactly as if they had been appended to the
        FIRST part — merging never shifts the engine's hazards."""
        late = FaultRule("ledger.batch", "latency", prob=0.5,
                         latency_s=0.0)

        def drive(plan, idx):
            for _ in range(200):
                plan.fire("ledger.batch")
            return {(s, h) for (s, h, _k, i) in plan.fired if i == idx}

        merged = merge_plans(self._part_a(), self._part_b())
        merged.extend([late])
        solo = FaultPlan(
            seed=7, rules=list(self._part_a().rules) + [late], sleep=_noop
        )
        assert drive(merged, len(merged.rules) - 1) == drive(
            solo, len(solo.rules) - 1
        )


# ----------------------------------------------------- scenario engine


class TestScenarioEngine:
    def teardown_method(self):
        clear_current_event()

    def test_event_validation(self):
        with pytest.raises(ValueError, match="unknown scenario"):
            ScenarioEvent("e", 0, "explode", "storage.kv.get")
        with pytest.raises(ValueError, match="needs a site"):
            ScenarioEvent("e", 0, "die")
        with pytest.raises(ValueError, match="not a registered"):
            ScenarioEvent("e", 0, "die", "made.up.seam")
        with pytest.raises(ValueError, match="negative"):
            ScenarioEvent("e", -1, "join")
        with pytest.raises(ValueError, match="duplicate"):
            Scenario(0, [ScenarioEvent("e", 0, "join"),
                         ScenarioEvent("e", 1, "fork")])

    def test_schedule_is_height_sorted_and_insertion_stable(self):
        sc = Scenario(3, [
            ScenarioEvent("late", 9, "die", "collector.persist"),
            ScenarioEvent("first", 2, "join"),
            ScenarioEvent("also-first", 2, "fork"),
        ])
        assert [e[0] for e in sc.schedule()] == [
            "first", "also-first", "late",
        ]
        # pure function of construction inputs: rebuild == rebuild
        again = Scenario(3, [
            ScenarioEvent("late", 9, "die", "collector.persist"),
            ScenarioEvent("first", 2, "join"),
            ScenarioEvent("also-first", 2, "fork"),
        ])
        assert sc.schedule() == again.schedule()

    def test_seam_event_arms_after_current_hit_count(self):
        plan = FaultPlan(seed=0, sleep=_noop)
        for _ in range(3):
            plan.fire("storage.node.get")
        engine = ScenarioEngine(Scenario(0, [
            ScenarioEvent("kill", 4, "die", "storage.node.get",
                          {"after_hits": 1}),
        ]), plan)
        assert engine.step(3) == []  # not due yet
        fired = engine.step(4)
        assert [e.event_id for e in fired] == ["kill"]
        assert engine.done() and engine.remaining() == 0
        plan.fire("storage.node.get")  # hit 4: inside the grace window
        with pytest.raises(InjectedDeath):
            plan.fire("storage.node.get")  # hit 5: armed rule fires
        assert engine.step(9) == []  # an event fires exactly once

    def test_hooks_receive_event_and_missing_hook_is_rejected(self):
        got = []
        engine = ScenarioEngine(
            Scenario(0, [ScenarioEvent("f", 1, "fork",
                                       params={"ancestor": 5})]),
            FaultPlan(seed=0, sleep=_noop),
            hooks={"fork": got.append},
        )
        engine.step(1)
        assert got[0].event_id == "f" and got[0].params["ancestor"] == 5
        with pytest.raises(ValueError, match="no hook registered"):
            ScenarioEngine(
                Scenario(0, [ScenarioEvent("j", 0, "join")]),
                FaultPlan(seed=0, sleep=_noop),
            )

    def test_current_event_id_is_sticky_until_cleared(self):
        plan = FaultPlan(seed=0, sleep=_noop)
        engine = ScenarioEngine(Scenario(0, [
            ScenarioEvent("a", 1, "latency", "storage.kv.get",
                          {"latency_s": 0.0}),
            ScenarioEvent("b", 2, "latency", "storage.kv.get",
                          {"latency_s": 0.0}),
        ]), plan)
        engine.step(1)
        assert current_event_id() == "a"
        engine.step(2)
        assert current_event_id() == "b"  # last fired wins
        clear_current_event()
        assert current_event_id() is None
        assert engine.events_by_kind == {"latency": 2}

    def test_quiet_deaths_swallows_only_injected_death(self):
        seen = []
        prev = threading.excepthook
        threading.excepthook = lambda args: seen.append(args.exc_type)
        try:
            with quiet_deaths():
                def die():
                    raise InjectedDeath("fail-stop")

                def boom():
                    raise ValueError("real bug")

                for target in (die, boom):
                    t = threading.Thread(target=target)
                    t.start()
                    t.join()
            assert seen == [ValueError]
            # the previous hook is restored on exit
            assert threading.excepthook is not prev
        finally:
            threading.excepthook = prev


# ------------------------------------------------- invariants plumbing


class TestInvariantReport:
    def test_report_collects_failures_and_raises(self):
        report = InvariantReport()
        report.add(InvariantResult("ryw", True))
        bad = report.add(InvariantResult("roots", False, "hash mismatch"))
        assert not bad and not report.ok
        assert report.failures == [bad]
        assert report.summary() == {"ryw": True, "roots": False}
        with pytest.raises(AssertionError, match="hash mismatch"):
            report.raise_if_failed()

    def test_record_run_feeds_registry_families(self):
        before = gameday_stats().runs
        report = InvariantReport()
        report.add(InvariantResult("roots", True))
        record_run({"die": 2}, report)
        stats = gameday_stats()
        assert stats.runs == before + 1
        names = {s[0] for s in stats.samples()}
        assert {
            "khipu_gameday_runs_total",
            "khipu_gameday_events_total",
            "khipu_gameday_invariant_checks_total",
            "khipu_gameday_invariant_failures_total",
            "khipu_gameday_last_p99_ms",
        } <= names


# ------------------------------------------- watchdog scenario label


class TestWatchdogScenarioLabel:
    """Satellite: a watchdog trip during a gameday run is attributable
    to the hazard that preceded it — khipu_watchdog_trips_total grows
    a scenario="<event id>" labeled sample, while the unlabeled
    per-kind family (what dashboards and the bench smokes pin) stays
    byte-identical in shape."""

    def teardown_method(self):
        clear_current_event()

    def test_trip_carries_scenario_event_id_label(self):
        depth = {"d": 0}
        dog = Watchdog(
            config=TelemetryConfig(enabled=True, journal_runaway_depth=2),
            journal_depth=lambda: depth["d"],
            registry=MetricsRegistry(),
        )
        engine = ScenarioEngine(Scenario(1, [
            ScenarioEvent("gd.slow", 0, "latency", "storage.node.get",
                          {"latency_s": 0.0}),
        ]), FaultPlan(seed=1, sleep=_noop))
        engine.step(0)
        assert current_event_id() == "gd.slow"
        depth["d"] = 5
        assert dog.check_once(now=1.0) == ["journal_runaway"]
        kind, tags = dog.events[-1]
        assert kind == "journal_runaway"
        assert tags["scenario"] == "gd.slow"
        assert dog.scenario_trips[("journal_runaway", "gd.slow")] == 1

        text = dog.registry.prometheus_text()
        # base per-kind sample unchanged (the smoke-pinned shape)...
        assert 'khipu_watchdog_trips_total{kind="journal_runaway"} 1' \
            in text
        # ...plus the appended scenario-labeled sample
        labeled = [
            line for line in text.splitlines()
            if line.startswith("khipu_watchdog_trips_total{")
            and 'scenario="gd.slow"' in line
        ]
        assert len(labeled) == 1
        assert 'kind="journal_runaway"' in labeled[0]
        assert labeled[0].endswith(" 1")

    def test_trip_outside_a_scenario_stays_unlabeled(self):
        clear_current_event()
        depth = {"d": 9}
        dog = Watchdog(
            config=TelemetryConfig(enabled=True, journal_runaway_depth=2),
            journal_depth=lambda: depth["d"],
            registry=MetricsRegistry(),
        )
        assert dog.check_once(now=1.0) == ["journal_runaway"]
        assert dog.scenario_trips == {}
        kind, tags = dog.events[-1]
        assert kind == "journal_runaway" and "scenario" not in tags
        assert "scenario=" not in dog.registry.prometheus_text()


# ------------------------------------------------------ seam audit


def _seam_call_sites():
    """AST-walk every ``fault_point``/``fault_value`` call in
    khipu_tpu/: literal sites exactly, f-string sites by their literal
    prefix. A non-literal site name is itself a failure — the registry
    audit cannot see through one."""
    exact, prefixes = set(), set()
    for path in sorted((REPO / "khipu_tpu").rglob("*.py")):
        for node in ast.walk(ast.parse(path.read_text(encoding="utf-8"))):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else getattr(
                fn, "attr", ""
            )
            if name not in ("fault_point", "fault_value"):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                exact.add(arg.value)
            elif isinstance(arg, ast.JoinedStr) and arg.values:
                head = arg.values[0]
                prefix = (
                    head.value
                    if isinstance(head, ast.Constant)
                    and isinstance(head.value, str) else ""
                )
                assert prefix, (
                    f"{path}: parameterised seam with no literal prefix"
                )
                prefixes.add(prefix)
            else:
                raise AssertionError(
                    f"{path}: seam name is not a (f-)string literal"
                )
    return exact, prefixes


class TestSeamAudit:
    """Satellite meta-test: a chaos seam cannot ship unregistered or
    unexercised. The registry (chaos.plan.KNOWN_SEAMS) is the single
    source of truth the scenario DSL validates against, so a hole here
    is a hazard a gameday could never script."""

    def test_every_call_site_is_registered(self):
        exact, prefixes = _seam_call_sites()
        assert exact, "seam walk found nothing — the audit is broken"
        unregistered = sorted(s for s in exact if not known_seam(s))
        assert not unregistered, (
            f"fault seams missing from KNOWN_SEAMS: {unregistered}"
        )
        for prefix in sorted(prefixes):
            assert known_seam(prefix + "x"), (
                f"parameterised seam {prefix}* has no wildcard entry "
                "in KNOWN_SEAMS"
            )

    def test_registry_has_no_stale_entries(self):
        exact, prefixes = _seam_call_sites()
        for seam in sorted(KNOWN_SEAMS):
            if seam.endswith("*"):
                stem = seam[:-1]
                assert any(
                    p.startswith(stem) or stem.startswith(p)
                    for p in prefixes
                ), f"KNOWN_SEAMS entry {seam} matches no call site"
            else:
                assert seam in exact, (
                    f"KNOWN_SEAMS entry {seam} matches no call site"
                )

    def test_every_seam_is_exercised_by_some_test(self):
        corpus = (REPO / "bench.py").read_text(encoding="utf-8")
        corpus += "".join(
            p.read_text(encoding="utf-8")
            for p in sorted((REPO / "tests").glob("*.py"))
        )
        unexercised = sorted(
            seam for seam in KNOWN_SEAMS
            if (seam[:-1] if seam.endswith("*") else seam) not in corpus
        )
        assert not unexercised, (
            f"chaos seams referenced by no test or bench: {unexercised}"
        )


# --------------------------------------- previously-unexercised seams


class _FakeShard:
    """Minimal BridgeClient stand-in (tests/test_cluster.py shape)."""

    def __init__(self):
        self.store = {}

    def get_node_data(self, hashes):
        return {h: self.store[h] for h in hashes if h in self.store}

    def put_node_data(self, nodes):
        self.store.update(nodes)
        return len(nodes)

    def stream_node_data(self, ranges, cursor, count):
        snap = dict(self.store)
        keys = sorted(
            k for k in snap
            if cursor < k and any(lo <= _point(k) < hi
                                  for lo, hi in ranges)
        )
        page = keys[:count]
        done = len(keys) <= count
        nxt = page[-1] if page else bytes(cursor)
        return done, nxt, [(k, snap[k]) for k in page]

    def ping(self, payload=b""):
        return payload

    def close(self):
        pass


def _make_cluster(members, extra=(), **kwargs):
    shards = {ep: _FakeShard() for ep in (*members, *extra)}
    kwargs.setdefault("replication", 2)
    kwargs.setdefault("vnodes", 8)
    kwargs.setdefault("max_retries", 1)
    kwargs.setdefault("sleep", _noop)
    cl = ShardedNodeClient(
        list(members), channel_factory=lambda ep: shards[ep], **kwargs
    )
    return cl, shards


class TestSeamCoverage:
    """Targeted exercises for the seams the audit found dark: the
    storage put/get seams, the replicate fan-out, and the raw segment
    chunk data seam."""

    def test_kv_put_raise_is_fail_stop(self):
        src = MemoryKeyValueDataSource()
        with active(FaultPlan(seed=3, rules=[
                FaultRule("storage.kv.put", "raise", times=1)])):
            with pytest.raises(InjectedFault):
                src.update([], {b"k1": b"v1"})
            assert src.get(b"k1") is None  # nothing half-applied
            src.update([], {b"k1": b"v1"})  # fire budget spent: lands
        assert src.get(b"k1") == b"v1"

    def test_node_put_die_is_fail_stop(self):
        src = MemoryNodeDataSource()
        value = b"trie node rlp bytes"
        key = keccak256(value)
        with active(FaultPlan(seed=5, rules=[
                FaultRule("storage.node.put", "die", times=1)])):
            with pytest.raises(InjectedDeath):
                src.update([], {key: value})
            assert src.get(key) is None
        src.update([], {key: value})
        assert src.get(key) == value

    def test_block_get_latency_delays_without_corrupting(self):
        slept = []
        src = MemoryBlockDataSource()
        src.put(3, b"block three rlp")
        with active(FaultPlan(seed=4, rules=[
                FaultRule("storage.block.get", "latency",
                          latency_s=0.25)], sleep=slept.append)):
            assert src.get(3) == b"block three rlp"
        assert slept == [0.25]
        assert src.best_block_number == 3

    def test_replicate_raise_is_retryable_and_places_all(self):
        cl, shards = _make_cluster(["s0", "s1", "s2"])
        data = {
            keccak256(v): v
            for v in (b"gameday replicate %d" % i for i in range(40))
        }
        try:
            with active(FaultPlan(seed=2, rules=[
                    FaultRule("cluster.replicate", "raise", times=1)])):
                with pytest.raises(InjectedFault):
                    cl.replicate(data)
                # fail-stop at the seam: no shard saw a partial batch
                assert all(not s.store for s in shards.values())
                placed = cl.replicate(data)
            assert placed == 2 * len(data)  # replication=2
            assert cl.fetch(list(data)) == data
        finally:
            cl.close()

    def test_client_call_seam_fires_before_the_wire(self):
        """``bridge.call.*`` sits at the top of the client's ``_call``
        — a raise rule models an unreachable shard without a network:
        no server listens here, yet the seam fires first."""
        pytest.importorskip("grpc")
        from khipu_tpu.bridge import BridgeClient

        client = BridgeClient("127.0.0.1:9", deadline=0.5)
        try:
            with active(FaultPlan(seed=9, rules=[
                    FaultRule("bridge.call.Ping", "raise",
                              times=None)])):
                with pytest.raises(InjectedFault):
                    client.ping()
        finally:
            client.close()

    def test_compact_raise_leaves_store_serving(self, tmp_path):
        st = Storages(engine="kesque", data_dir=str(tmp_path))
        bc = Blockchain(st, CFG)
        bc.load_genesis(GEN)
        root = bc.get_header_by_number(0).state_root
        store = st.kesque_engine.store("account")
        oracle = {k: store.get(k) for k in store.keys()}
        assert oracle
        try:
            with active(FaultPlan(seed=6, rules=[
                    FaultRule("kesque.compact", "raise", times=1)])):
                with pytest.raises(InjectedFault):
                    st.kesque_engine.compact(root)
                # fail-stop before the freeze: every record intact
                for k, v in oracle.items():
                    assert store.get(k) == v
                report = st.kesque_engine.compact(root)
            assert report.corrupt == 0
            for k in store.keys():
                assert store.get(k) == oracle[k]
        finally:
            st.stop()

    def test_ingest_raise_then_retry_completes(self, tmp_path):
        """``kesque.ingest`` fires per fetched chunk inside the pull
        workers; a raise surfaces through the pool and the retry
        re-ships the whole manifest (nothing landed before the seam)."""
        from khipu_tpu.sync.fast_sync import segment_snapshot_ingest

        src = Storages(engine="kesque", data_dir=str(tmp_path / "src"))
        dst = Storages(engine="kesque", data_dir=str(tmp_path / "dst"))
        data = {
            keccak256(v): v
            for v in (b"gameday ingest node %d" % i for i in range(64))
        }
        src.kesque_engine.store("account").append_batch([], data)
        eng = src.kesque_engine
        try:
            with active(FaultPlan(seed=8, rules=[
                    FaultRule("kesque.ingest", "raise", times=1)])):
                with pytest.raises(InjectedFault):
                    segment_snapshot_ingest(
                        dst, eng.list_segments, eng.read_chunk,
                        workers=1,
                    )
                report = segment_snapshot_ingest(
                    dst, eng.list_segments, eng.read_chunk, workers=1,
                )
            assert report.records == len(data)
            assert report.corrupt_frames == 0
            dstore = dst.kesque_engine.store("account")
            for k, v in data.items():
                assert dstore.get(k) == v
        finally:
            src.stop()
            dst.stop()

    def test_corrupt_segment_chunk_dies_at_receiver_scan(self, tmp_path):
        """``bridge.segment.raw`` corrupt seam end to end over a real
        gRPC loopback: the per-frame CRC fence means a receiver that
        scans before admitting (the rebalancer/ingest contract) rejects
        ANY bit-flipped chunk."""
        pytest.importorskip("grpc")
        from khipu_tpu.bridge import BridgeClient, BridgeServer
        from khipu_tpu.storage.segment import scan_frames

        st = Storages(engine="kesque", data_dir=str(tmp_path))
        data = {
            keccak256(v): v
            for v in (b"gameday segment node %d" % i for i in range(64))
        }
        st.kesque_engine.store("account").append_batch([], data)
        server = BridgeServer(Blockchain(st, CFG), CFG)
        port = server.start(port=0)
        client = BridgeClient(f"127.0.0.1:{port}", deadline=5.0)
        try:
            name, manifest = client.engine_info()
            assert name == "kesque" and manifest
            topic, seq, _size = manifest[0]
            raw, _nxt, _done = client.stream_segments(topic, seq, 0,
                                                      1 << 20)
            frames, end = scan_frames(raw)
            assert frames and end == len(raw)  # clean: whole frames
            with active(FaultPlan(seed=21, rules=[
                    FaultRule("bridge.segment.raw", "corrupt")])):
                bad, _n, _d = client.stream_segments(topic, seq, 0,
                                                     1 << 20)
            assert bad != raw  # the data seam really fired
            _frames, end_bad = scan_frames(bad)
            assert end_bad != len(bad)  # CRC fence: chunk rejected
        finally:
            client.close()
            server.stop()


# ------------------------------------- reorg-during-rebalance fence


class TestReorgDuringRebalance:
    def test_reorg_fences_while_rebalancer_streams(self, fork_chains):
        """Named regression for the gameday's nastiest pairing: a fork
        battle retracting served blocks WHILE a shard join streams.
        The reorg's fence (journal recovery pass, overlay
        invalidation) must not perturb the epoch fence — the join
        stays in flight against the committed epoch, writes made
        mid-switch land in BOTH epochs' owners, and the ring commits
        at exactly old+1 afterwards."""
        cl, shards = _make_cluster(["s0", "s1"], extra=("s2",))
        rb = Rebalancer(cl, batch=32)
        data = {
            keccak256(v): v
            for v in (b"reorg x rebalance %d" % i for i in range(300))
        }
        cl.replicate(data)
        e0 = cl.ring.epoch

        gate = threading.Event()
        streaming = threading.Event()

        def slow_stream(self, ranges, cursor, count,
                        _orig=_FakeShard.stream_node_data):
            streaming.set()
            assert gate.wait(30), "test gate never released"
            return _orig(self, ranges, cursor, count)

        for ep in ("s0", "s1"):  # either source replica may serve
            shards[ep].stream_node_data = slow_stream.__get__(shards[ep])

        join_box = {}

        def run_join():
            try:
                join_box["streamed"] = rb.join("s2")
            except BaseException as e:  # surfaced by the asserts below
                join_box["error"] = e

        join_t = threading.Thread(target=run_join, daemon=True)
        join_t.start()
        try:
            assert streaming.wait(30), "join never reached the stream"
            assert rb.in_transition and cl.ring.epoch == e0

            # the fork battle, mid-stream: an 8-block primary adopts
            # the heavier 10-block branch diverging at 5
            bc = _fresh(CFG)
            driver = ReplayDriver(bc, CFG)
            stats = ReplayStats()
            for b in fork_chains["base"]:
                driver._execute_and_insert(b, stats)
            mgr = ReorgManager(bc, CFG, driver=driver)
            adopted = mgr.switch(5, fork_chains["fork"][5:])
            assert adopted == 5
            assert bc.best_block_number == 10
            assert check_roots_bit_exact(bc, fork_chains["fork_bc"]).ok

            # the switch (and its fence/recovery pass) left the shard
            # plane's epoch fence alone: still the committed epoch,
            # still streaming
            assert cl.ring.epoch == e0 and rb.in_transition

            # a write landed mid-switch goes to BOTH epochs' owners
            extra_val = b"written during the fork battle"
            extra_key = keccak256(extra_val)
            cl.replicate({extra_key: extra_val})
        finally:
            gate.set()
        join_t.join(timeout=60)
        assert not join_t.is_alive(), "join wedged behind the reorg"
        assert "error" not in join_box, join_box.get("error")
        assert join_box["streamed"] > 0

        # exactly-old-or-new, landed at new
        assert check_epoch(rb, e0, e0 + 1).ok
        assert cl.ring.epoch == e0 + 1
        assert set(cl.ring.members) == {"s0", "s1", "s2"}
        # every key (including the mid-switch write) still fetchable
        want = dict(data)
        want[extra_key] = extra_val
        keys = sorted(want)
        got = {}
        for i in range(0, len(keys), 128):
            got.update(cl.fetch(keys[i:i + 128]))
        assert got == want
        cl.close()


# -------------------------------------------- pairwise hazard matrix


# Hazard vocabulary for the matrix: four seeded deaths at distinct
# collector stage boundaries (each is a different crash window of the
# windowed pipeline) plus a benign slow-disk hazard, so pairs compose
# fail-stop x fail-stop AND fail-stop x gray-failure.
HAZARDS = {
    "seal_die": ("collector.seal", "die"),
    "pack_die": ("collector.pack", "die"),
    "persist_die": ("collector.persist", "die"),
    "save_die": ("collector.save", "die"),
    "slow_node": ("storage.node.get", "latency"),
}
MATRIX_SEEDS = range(6)


def _hazard_params(name, kind, seed, salt):
    if kind == "latency":
        return {"latency_s": 0.0, "prob": 0.2, "times": None}
    # the arm depth decides killed vs survived: deep enough and the
    # run outlives the rule — both outcomes MUST occur across the
    # sweep (asserted below), or the matrix proves nothing
    return {"after_hits": derive(seed, salt, 8), "times": 1}


def _run_matrix_cell(chain, a, b, seed):
    """One composed run: hazard ``a`` at height h1, hazard ``b`` at a
    later height, both armed through the scenario engine onto ONE
    plan, over the windowed replay pipeline. Returns (blockchain,
    engine, plan, deaths)."""
    site_a, kind_a = HAZARDS[a]
    site_b, kind_b = HAZARDS[b]
    h1 = 2 + derive(seed, f"{a}>{b}:h1", 4)
    h2 = h1 + 1 + derive(seed, f"{a}>{b}:h2", 4)
    scenario = Scenario(seed, [
        ScenarioEvent("hz.a", h1, kind_a, site_a,
                      _hazard_params("a", kind_a, seed, f"{a}>{b}:a")),
        ScenarioEvent("hz.b", h2, kind_b, site_b,
                      _hazard_params("b", kind_b, seed, f"{a}>{b}:b")),
    ])
    plan = FaultPlan(seed=seed, sleep=_noop)
    engine = ScenarioEngine(scenario, plan)
    cfg = _windowed_cfg()
    bc = _fresh(cfg)
    deaths = 0
    with quiet_deaths(), active(plan):
        guard = 0
        while bc.best_block_number < N_BLOCKS:
            guard += 1
            assert guard < 64, f"matrix cell {a}>{b}@{seed} wedged"
            engine.step(bc.best_block_number)
            start = bc.best_block_number
            try:
                ReplayDriver(bc, cfg).replay(chain[start:start + 2])
            except CollectorDied:
                deaths += 1
                ReplayDriver(bc, cfg).recover()
                assert bc.storages.window_journal.pending() == []
        engine.step(bc.best_block_number)
    assert engine.done(), engine.remaining()
    return bc, engine, plan, deaths


class TestHazardMatrix:
    def test_pairwise_hazard_matrix_120_runs_bit_exact(self, chain,
                                                       reference):
        """Tentpole acceptance: every ordered pair of hazard kinds x 6
        seeds (20 x 6 = 120 composed runs). Whatever the pair kills,
        journal recovery resumes to the BIT-EXACT chain; the sweep
        exercises both outcomes (killed > 20 AND survived > 20); every
        run's outcome feeds the khipu_gameday_* families."""
        pairs = [
            (a, b) for a in HAZARDS for b in HAZARDS if a != b
        ]
        assert len(pairs) == 20
        runs = killed = survived = 0
        for a, b in pairs:
            for seed in MATRIX_SEEDS:
                bc, engine, _plan, deaths = _run_matrix_cell(
                    chain, a, b, seed
                )
                runs += 1
                if deaths:
                    killed += 1
                else:
                    survived += 1
                result = check_roots_bit_exact(bc, reference)
                assert result.ok, (
                    f"{a}>{b}@{seed}: {result.detail} "
                    f"(fired {engine.fired})"
                )
                report = InvariantReport()
                report.add(result)
                record_run(engine.events_by_kind, report)
        assert runs == 120
        assert killed > 20 and survived > 20, (killed, survived)
        assert gameday_stats().runs >= runs

    def test_matrix_cells_are_deterministic(self, chain):
        """Same (pair, seed) => identical event schedule, identical
        fired-fault log, identical final root — the replayability
        claim a gameday postmortem depends on."""
        for a, b, seed in [
            ("persist_die", "save_die", 3),
            ("slow_node", "seal_die", 1),
        ]:
            outcomes = []
            for _ in range(2):
                bc, engine, plan, deaths = _run_matrix_cell(
                    chain, a, b, seed
                )
                outcomes.append((
                    engine.scenario.schedule(),
                    list(engine.fired),
                    list(plan.fired),
                    deaths,
                    bc.get_header_by_number(
                        bc.best_block_number
                    ).state_root,
                ))
            assert outcomes[0] == outcomes[1]
