"""Conformance corpus (khipu_tpu/statetest.py over tests/fixtures/
state_tests/ — the ethereum/tests GeneralStateTest filler shape).

Every fixture file runs through the REAL execution stack (Ledger ->
EVM -> trie commit) and every case must land on the filler's post
state root exactly. ``bench.py --conformance`` runs the SAME corpus
and gates ``statetest_pass_rate`` at 1.0; this marks the corpus as a
pytest surface so tier-1 catches a regression without the bench.
"""

import glob
import os

import pytest

from khipu_tpu.statetest import run_file

pytestmark = pytest.mark.conformance

CORPUS_DIR = os.path.join(
    os.path.dirname(__file__), "fixtures", "state_tests"
)
CORPUS = sorted(glob.glob(os.path.join(CORPUS_DIR, "*.json")))


def test_corpus_is_present():
    """The corpus shrinking silently would gate nothing — pin the
    floor (6 files as of PR 20; add, don't remove)."""
    assert len(CORPUS) >= 6, f"state test corpus missing: {CORPUS_DIR}"


@pytest.mark.parametrize(
    "path", CORPUS, ids=[os.path.basename(p) for p in CORPUS]
)
def test_statetest_file_passes(path):
    results = run_file(path)
    assert results, f"{path}: no runnable cases"
    failures = [
        f"{r.name}[{r.fork}#{r.index}]" for r in results if not r.ok
    ]
    assert not failures, f"{os.path.basename(path)}: {failures}"
