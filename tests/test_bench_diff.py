"""Differential bench attribution (bench.py --diff).

The analyzer's contract, pinned here with doctored capture pairs:

* identical captures diff to NO attribution at all (the tolerance
  floor absorbs byte-identical and near-identical reruns);
* when exactly one sub-phase site regresses (the doctored pair grows
  ``seal.upload`` by 252 KB/block), the attribution names THAT site
  and the diff exits non-zero — the line that would have reduced the
  r05->r06 regression hunt to one grep.
"""

import copy
import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import bench  # noqa: E402


def _line():
    return {
        "metric": "replay_parallel_commit_fixture_blocks_per_sec",
        "value": 100.0,
        "unit": "blocks/s",
        "phases": {"execute": 2.0, "seal": 1.0, "collect": 0.5,
                   "_bg": "collector"},
        "movement": {
            "device_bytes_total": {"h2d": 4096 * 32, "d2h": 512 * 32},
            "ledger_blocks": 32,
            "bytes_per_block_by_phase": {
                "seal": {"h2d": 4096},
                "collect": {"d2h": 512},
            },
            "bytes_per_block_by_subphase": {
                "seal.upload": {"h2d": 3072},
                "seal.alias_gather": {"h2d": 1024},
                "seal.rootcheck": {"d2h": 256},
            },
        },
    }


def _doc(lines):
    return {
        "cmd": "test", "rc": 0,
        "tail": "\n".join(json.dumps(x) for x in lines),
        "parsed": lines[-1],
    }


def _doctor_upload(line, extra_bytes=258048, slower=True):
    """Grow seal.upload (and its seal rollup) by ``extra_bytes``/block
    — 258048 = 252 KB, the shape of a seal-side upload regression."""
    new = copy.deepcopy(line)
    if slower:
        new["value"] = 80.0
        new["phases"]["seal"] = 1.7
    new["movement"]["bytes_per_block_by_phase"]["seal"]["h2d"] += (
        extra_bytes
    )
    new["movement"]["bytes_per_block_by_subphase"]["seal.upload"][
        "h2d"
    ] += extra_bytes
    return new


class TestDiffLines:
    def test_identical_lines_produce_no_attribution(self):
        line = _line()
        d = bench.diff_lines(line, copy.deepcopy(line))
        assert d["attributions"] == []
        assert not d["regressed"]
        assert d["ratio"] == 1.0

    def test_noise_within_tolerance_is_silent(self):
        """Small wobble in every series — a honest rerun — attributes
        nothing: bytes under both the absolute and relative floors,
        phases under the relative floor, blocks/s above the ratio."""
        line = _line()
        new = copy.deepcopy(line)
        new["value"] = 95.0  # 0.95x > 0.9 floor
        new["phases"]["seal"] = 1.1  # +10% < 20% rel floor
        new["movement"]["bytes_per_block_by_subphase"]["seal.upload"][
            "h2d"
        ] += 64  # < 1024 abs floor
        d = bench.diff_lines(line, new)
        assert d["attributions"] == []
        assert not d["regressed"]

    def test_single_subphase_regression_is_attributed(self):
        line = _line()
        d = bench.diff_lines(line, _doctor_upload(line))
        assert d["regressed"]
        joined = "\n".join(d["attributions"])
        assert "seal.upload +252.0 KB/block" in joined
        assert "(h2d" in joined
        # the untouched sites stay out of the attribution
        assert "alias_gather" not in joined
        assert "rootcheck" not in joined

    def test_byte_growth_alone_regresses(self):
        """Measured bytes are deterministic facts, not wall-clock
        noise: growth past tolerance counts as a regression even when
        blocks/s holds (the machine may just be less loaded today)."""
        line = _line()
        new = _doctor_upload(line, slower=False)
        d = bench.diff_lines(line, new)
        assert d["regressed"]
        assert any("seal.upload" in a for a in d["attributions"])
        assert not any("blocks/s" in a for a in d["attributions"])

    def test_phase_seconds_attribute_but_do_not_gate(self):
        """Wall seconds are attribution-only: a phase doubling names
        itself in the report, but noise-prone clocks never flip the
        exit code by themselves."""
        line = _line()
        new = copy.deepcopy(line)
        new["phases"]["seal"] = 2.5
        d = bench.diff_lines(line, new)
        assert not d["regressed"]
        assert any(
            a.startswith("phase seal +1.50 s") for a in d["attributions"]
        )

    def test_non_numeric_phase_entries_are_ignored(self):
        line = _line()
        new = copy.deepcopy(line)
        new["phases"]["_bg"] = "collector,persister"  # annotation row
        d = bench.diff_lines(line, new)
        assert d["attributions"] == []

    def test_missing_movement_in_base_still_diffs(self):
        """Diffing against a pre-ledger capture (BENCH_r05 shape — no
        movement block) treats the base as zero and attributes the NEW
        capture's bytes only past tolerance vs zero."""
        base = _line()
        del base["movement"]
        d = bench.diff_lines(base, _line())
        # all three sub-phase sites grew from nothing
        assert any("seal.upload" in a for a in d["attributions"])


class TestDiffCaptures:
    def test_identical_captures_no_attribution(self):
        base = {"m1": _line()}
        r = bench.diff_captures(base, copy.deepcopy(base))
        assert r["attributions"] == []
        assert not r["regressed"]
        assert r["compared"] == ["m1"]
        assert r["skipped"] == []

    def test_regression_names_metric_and_site(self):
        line = _line()
        r = bench.diff_captures(
            {"m1": line}, {"m1": _doctor_upload(line)}
        )
        assert r["regressed"]
        assert any(
            a.startswith("m1: ") and "seal.upload" in a
            for a in r["attributions"]
        )

    def test_disjoint_metrics_are_skipped_not_diffed(self):
        line = _line()
        other = dict(_line(), metric="m2")
        r = bench.diff_captures({"m1": line}, {"m2": other})
        assert r["compared"] == []
        assert sorted(r["skipped"]) == ["m1", "m2"]
        assert not r["regressed"]

    def test_gate_line_is_not_a_measurement(self):
        line = _line()
        gate = {"metric": "bench_compare", "value": 0}
        r = bench.diff_captures(
            {"m1": line, "bench_compare": gate},
            {"m1": copy.deepcopy(line), "bench_compare": gate},
        )
        assert r["compared"] == ["m1"]
        assert "bench_compare" not in r["metrics"]


class TestDiffCLI:
    """bench.py --diff=BASE.json --diff-to=NEW.json end to end: the
    offline mode bench_gate.sh's attribution rides on."""

    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "bench.py", *args],
            cwd=REPO_ROOT, capture_output=True, text=True,
        )

    def test_doctored_pair_attributes_and_exits_nonzero(self, tmp_path):
        line = _line()
        base = tmp_path / "base.json"
        new = tmp_path / "new.json"
        base.write_text(json.dumps(_doc([line])))
        new.write_text(json.dumps(_doc([_doctor_upload(line)])))
        r = self._run(f"--diff={base}", f"--diff-to={new}")
        assert r.returncode == 1, r.stderr
        assert "seal.upload +252.0 KB/block" in r.stderr

    def test_identical_pair_exits_zero_with_no_attribution(
            self, tmp_path):
        line = _line()
        base = tmp_path / "base.json"
        base.write_text(json.dumps(_doc([line])))
        r = self._run(f"--diff={base}", f"--diff-to={base}")
        assert r.returncode == 0, r.stderr
        assert "no attribution" in r.stderr

    def test_diff_without_diff_to_is_a_usage_error(self, tmp_path):
        base = tmp_path / "base.json"
        base.write_text(json.dumps(_doc([_line()])))
        r = self._run(f"--diff={base}")
        assert r.returncode == 2
        assert "--diff-to" in r.stderr
