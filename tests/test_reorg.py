"""Reorg-safe pipelined commit (sync/reorg.py, sync/journal.py
REORG-INTENT records — docs/recovery.md crash-point table).

The headline guarantees: a TD-tie NEVER displaces our chain (strict
``>`` pinned); a journaled switch killed at ANY ``reorg.*`` seam
recovers to exactly the old chain or exactly the new one, state root
bit-exact vs a fresh replay of the winning branch (120-seed sweep);
filters retract orphaned logs with ``removed: true``; orphaned-only
txs re-enter the pool through the standard replacement rules — even
when the switch dies mid-flight (orphans ride in the intent record);
and a node serving reads DURING a reorg (plus one kill-and-recover)
never shows a balance outside the two legal chain states.
"""

import dataclasses
import threading

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.chaos import FaultPlan, FaultRule, InjectedDeath, active
from khipu_tpu.config import SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.jsonrpc.filters import FilterManager, LogHit, LogQuery
from khipu_tpu.serving.readview import ReadView
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.journal import ReorgRecord, recover
from khipu_tpu.sync.regular_sync import RegularSyncService, SyncAborted
from khipu_tpu.sync.reorg import ReorgManager, ReorgTooDeep
from khipu_tpu.sync.replay import ReplayDriver, ReplayStats
from khipu_tpu.txpool import PendingTransactionsPool

pytestmark = pytest.mark.chaos

CFG = dataclasses.replace(
    fixture_config(chain_id=1),
    sync=SyncConfig(commit_window_blocks=1, parallel_tx=False),
)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(4)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18
ALLOC = {a: 1000 * ETH for a in ADDRS}
GEN = GenesisSpec(alloc=ALLOC)
MINER_A = b"\xaa" * 20  # coinbase of the chain we leave
MINER_B = b"\xbb" * 20  # coinbase of the diverged suffix


def _tx(i, nonce, to, value, gas_price=10**9):
    return sign_transaction(
        Transaction(nonce, gas_price, 21_000, to, value),
        KEYS[i], chain_id=1,
    )


def build(n, diverge_at=None, value_off=0):
    """Consensus-true chain of ``n`` transfer blocks. From
    ``diverge_at`` on, the coinbase flips to MINER_B and tx values
    shift by ``value_off`` — same senders and nonces, DIFFERENT txs,
    so the losing branch has orphaned-only txs to recycle."""
    builder = ChainBuilder(Blockchain(Storages(), CFG), CFG, GEN)
    blocks, nonces = [], [0, 0, 0, 0]
    for k in range(n):
        i = k % 4
        diverged = diverge_at is not None and k >= diverge_at
        blocks.append(builder.add_block(
            [_tx(i, nonces[i], ADDRS[(i + 1) % 4],
                 100 + k + (value_off if diverged else 0))],
            coinbase=MINER_B if diverged else MINER_A,
            timestamp=10 * (k + 1),
        ))
        nonces[i] += 1
    return builder.blockchain, blocks


@pytest.fixture(scope="module")
def chains():
    """(base 8 blocks, fork 10 diverging at 5) — the fork's suffix
    carries different txs, so base blocks 6..8 hold 3 orphaned-only
    txs. Plus an equal-length equal-TD branch for the tie test, and a
    smaller pair for the seed sweep."""
    base_bc, base = build(8)
    fork_bc, fork = build(10, diverge_at=5, value_off=1000)
    _, tie = build(8, diverge_at=5, value_off=1000)
    sweep_base_bc, sweep_base = build(6)
    sweep_fork_bc, sweep_fork = build(8, diverge_at=3, value_off=500)
    return {
        "base_bc": base_bc, "base": base,
        "fork_bc": fork_bc, "fork": fork,
        "tie": tie,
        "sweep_base_bc": sweep_base_bc, "sweep_base": sweep_base,
        "sweep_fork_bc": sweep_fork_bc, "sweep_fork": sweep_fork,
    }


def fresh_node(blocks, upto, config=CFG):
    """A node synced through ``blocks[:upto]`` via the validated
    import path — the fresh-replay reference the sweep compares roots
    against is the ChainBuilder chain itself."""
    bc = Blockchain(Storages(), config)
    bc.load_genesis(GEN)
    driver = ReplayDriver(bc, config)
    stats = ReplayStats()
    for b in blocks[:upto]:
        driver._execute_and_insert(b, stats)
    return bc, driver


def _balance(bc, addr, number):
    header = bc.get_header_by_number(number)
    acct = bc.get_account(addr, header.state_root)
    return 0 if acct is None else acct.balance


# ------------------------------------------------------------ TD rule


class TestTdRule:
    def test_equal_td_branch_is_not_adopted(self, chains):
        """Strict ``>``: a same-length branch with identical
        difficulty per height ties on TD and MUST lose — first-seen
        wins, or every tie would thrash the chain."""
        bc, _ = fresh_node(chains["base"], 8)
        sync = RegularSyncService(bc, CFG, manager=None)
        branch = [b.header for b in chains["tie"][5:]]
        ancestor = bc.get_header_by_number(5)
        assert sync._maybe_reorg(branch, ancestor) is None
        assert bc.best_block_number == 8
        assert bc.get_hash_by_number(8) == chains["base"][7].hash

    def test_heavier_branch_is_accepted(self, chains):
        bc, _ = fresh_node(chains["base"], 8)
        sync = RegularSyncService(bc, CFG, manager=None)
        branch = [b.header for b in chains["fork"][5:]]
        ancestor = bc.get_header_by_number(5)
        assert sync._maybe_reorg(branch, ancestor) == branch

    def test_rollback_to_raises_on_chain_hole(self, chains):
        """The old silent ``break`` left best pointing above the
        highest surviving block; a hole now aborts the sync round."""
        bc, _ = fresh_node(chains["base"], 8)
        sync = RegularSyncService(bc, CFG, manager=None)
        bc.storages.block_header_storage.source.remove(7)
        with pytest.raises(SyncAborted, match="hole"):
            sync._rollback_to(5)


# ------------------------------------------------- journal round-trip


class TestReorgIntentJournal:
    def test_intent_record_round_trips(self, chains):
        bc, _ = fresh_node(chains["base"], 8)
        journal = bc.storages.window_journal
        old = [b.hash for b in chains["base"][5:]]
        adopted = chains["fork"][5:]
        orphans = [
            tx for b in chains["base"][5:] for tx in b.body.transactions
        ]
        anc = bc.get_header_by_number(5)
        seq = journal.log_reorg_intent(5, anc.hash, old, adopted,
                                       orphan_txs=orphans)
        (rec,) = journal.pending()
        assert isinstance(rec, ReorgRecord)
        assert rec.seq == seq
        assert rec.ancestor_number == 5
        assert rec.ancestor_hash == anc.hash
        assert rec.old_hashes == old
        assert rec.adopted_hashes == [b.hash for b in adopted]
        assert rec.old_top == 8 and rec.new_top == 10
        staged = journal.staged_blocks(rec)
        assert [b.hash for b in staged] == [b.hash for b in adopted]
        assert [t.hash for t in rec.orphan_txs()] == [
            t.hash for t in orphans
        ]

    def test_pending_intent_with_intact_chain_abandons(self, chains):
        """Kill after the intent fsync, before any removal: recovery
        finds the old chain whole and walks away from the switch."""
        bc, _ = fresh_node(chains["base"], 8)
        journal = bc.storages.window_journal
        anc = bc.get_header_by_number(5)
        journal.log_reorg_intent(
            5, anc.hash, [b.hash for b in chains["base"][5:]],
            chains["fork"][5:],
        )
        report = recover(bc, config=CFG)
        assert report.reorgs_abandoned == 1
        assert bc.best_block_number == 8
        assert bc.get_hash_by_number(8) == chains["base"][7].hash
        assert journal.pending() == []

    def test_torn_switch_rolls_forward_bit_exact(self, chains):
        """Old chain partially gone -> recovery re-executes the staged
        branch; the recovered tip state root matches the fresh-replay
        reference bit for bit."""
        bc, _ = fresh_node(chains["base"], 8)
        journal = bc.storages.window_journal
        anc = bc.get_header_by_number(5)
        journal.log_reorg_intent(
            5, anc.hash, [b.hash for b in chains["base"][5:]],
            chains["fork"][5:],
        )
        # tear the switch: the tip block is half-removed
        bc.remove_block(chains["base"][7].hash)
        report = recover(bc, config=CFG)
        assert report.reorgs_completed == 1
        assert bc.best_block_number == 10
        ref = chains["fork_bc"].get_header_by_number(10)
        assert bc.get_header_by_number(10).state_root == ref.state_root
        assert bc.get_hash_by_number(10) == chains["fork"][9].hash
        assert journal.pending() == []

    def test_mid_switch_death_recovery_recycles_orphans(self, chains):
        """The orphan txs ride in the intent record, so recovery can
        recycle them even though the rollback removed their bodies."""
        bc, driver = fresh_node(chains["base"], 8)
        pool = PendingTransactionsPool()
        mgr = ReorgManager(bc, CFG, driver=driver, txpool=pool)
        plan = FaultPlan(seed=7, rules=[
            FaultRule("reorg.adopt", "die", times=1, after=1)
        ])
        with pytest.raises(InjectedDeath):
            with active(plan):
                mgr.switch(5, chains["fork"][5:])
        report = recover(bc, config=CFG, txpool=pool)
        assert bc.best_block_number == 10
        assert any("recycled" in a for a in report.actions)
        orphan_hashes = {
            tx.hash for b in chains["base"][5:]
            for tx in b.body.transactions
        }
        assert orphan_hashes  # the fixture really diverges
        for h in orphan_hashes:
            assert pool.get(h) is not None


# ------------------------------------------------------ depth refusal


class TestDepthRefusal:
    def test_too_deep_reorg_refused_and_counted(self, chains):
        shallow = dataclasses.replace(
            CFG, db=dataclasses.replace(CFG.db, unconfirmed_depth=2)
        )
        bc, driver = fresh_node(chains["base"], 8, config=shallow)
        mgr = ReorgManager(bc, shallow, driver=driver)
        with pytest.raises(ReorgTooDeep):
            mgr.switch(5, chains["fork"][5:])  # depth 3 > 2
        assert mgr.refused == 1
        assert bc.best_block_number == 8  # untouched
        samples = {name: v for name, _k, _l, v in mgr._registry_samples()}
        assert samples["khipu_reorg_refused_total"] == 1
        assert samples["khipu_reorg_total"] == 0


# --------------------------------------------------- windowed adoption


class TestWindowedAdoption:
    def test_long_branch_adopts_through_windowed_pipeline(self, chains):
        cfg = dataclasses.replace(
            CFG, sync=SyncConfig(commit_window_blocks=3,
                                 parallel_tx=False),
        )
        bc, driver = fresh_node(chains["base"], 8, config=cfg)
        mgr = ReorgManager(bc, cfg, driver=driver)
        done = mgr.switch(5, chains["fork"][5:])
        assert done == 5
        assert bc.best_block_number == 10
        ref = chains["fork_bc"].get_header_by_number(10)
        assert bc.get_header_by_number(10).state_root == ref.state_root
        # every intent — the reorg's and the windowed adoption's —
        # is committed and pruned
        assert bc.storages.window_journal.pending() == []

    def test_clean_switch_counters(self, chains):
        bc, driver = fresh_node(chains["base"], 8)
        pool = PendingTransactionsPool()
        mgr = ReorgManager(bc, CFG, driver=driver, txpool=pool)
        mgr.switch(5, chains["fork"][5:])
        assert mgr.switches == 1
        assert mgr.last_depth == 3
        assert mgr.orphaned_blocks == 3
        assert mgr.recycled_txs == 3  # base 6..8 txs, all orphan-only
        assert mgr.watch_source() == 1


# ------------------------------------------------------ orphan recycling


class TestOrphanRecycling:
    def test_orphans_reenter_pool_after_switch(self, chains):
        bc, driver = fresh_node(chains["base"], 8)
        pool = PendingTransactionsPool()
        mgr = ReorgManager(bc, CFG, driver=driver, txpool=pool)
        mgr.switch(5, chains["fork"][5:])
        for b in chains["base"][5:]:
            for tx in b.body.transactions:
                assert pool.get(tx.hash) is not None

    def test_recycling_respects_replacement_rules(self, chains):
        """A pooled same-(sender,nonce) tx that outbids the orphan
        keeps its slot; a lower-bid pooled tx is replaced."""
        bc, driver = fresh_node(chains["base"], 8)
        pool = PendingTransactionsPool()
        orphans = [
            tx for b in chains["base"][5:] for tx in b.body.transactions
        ]
        rich = sign_transaction(
            Transaction(orphans[0].tx.nonce, 2 * 10**9, 21_000,
                        orphans[0].tx.to, 1),
            KEYS[5 % 4], chain_id=1,
        )
        poor = sign_transaction(
            Transaction(orphans[1].tx.nonce, 1, 21_000,
                        orphans[1].tx.to, 1),
            KEYS[6 % 4], chain_id=1,
        )
        assert pool.add(rich) and pool.add(poor)
        mgr = ReorgManager(bc, CFG, driver=driver, txpool=pool)
        mgr.switch(5, chains["fork"][5:])
        # orphan[0] (gas price 1 gwei) lost to the 2-gwei incumbent
        assert pool.get(rich.hash) is not None
        assert pool.get(orphans[0].hash) is None
        # orphan[1] outbid the 1-wei incumbent and took the slot
        assert pool.get(poor.hash) is None
        assert pool.get(orphans[1].hash) is not None
        assert mgr.recycled_txs == 2  # orphans[1] + orphans[2]

    def test_adopted_branch_txs_leave_the_pool(self, chains):
        bc, driver = fresh_node(chains["base"], 8)
        pool = PendingTransactionsPool()
        adopted_txs = [
            tx for b in chains["fork"][5:] for tx in b.body.transactions
        ]
        for tx in adopted_txs:
            assert pool.add(tx)
        mgr = ReorgManager(bc, CFG, driver=driver, txpool=pool)
        mgr.switch(5, chains["fork"][5:])
        for tx in adopted_txs:
            assert pool.get(tx.hash) is None


# ------------------------------------------------------- filter parity


class TestFilterParity:
    def _hit(self, number, address, removed=True):
        return LogHit(
            address=address, topics=(b"\x01" * 32,), data=b"",
            block_number=number, block_hash=b"\xcc" * 32,
            tx_hash=b"\xdd" * 32, tx_index=0, log_index=0,
            removed=removed,
        )

    def test_removed_retractions_delivered_before_new_results(
        self, chains
    ):
        bc, _ = fresh_node(chains["base"], 8)
        fm = FilterManager(bc)
        fid = fm.new_log_filter(
            LogQuery(from_block=0, to_block=None, addresses=(ADDRS[0],))
        )
        assert fm.changes(fid) == []  # cursor now at 8
        hit = self._hit(7, ADDRS[0])
        fm.note_reorg(5, [hit])
        out = fm.changes(fid)
        assert out and out[0] is hit and out[0].removed is True

    def test_non_matching_filter_gets_no_retraction(self, chains):
        bc, _ = fresh_node(chains["base"], 8)
        fm = FilterManager(bc)
        fid = fm.new_log_filter(
            LogQuery(from_block=0, to_block=None, addresses=(ADDRS[1],))
        )
        fm.changes(fid)
        fm.note_reorg(5, [self._hit(7, ADDRS[0])])
        assert fm.changes(fid) == []

    def test_filter_behind_the_fork_is_untouched(self, chains):
        """A filter whose cursor never crossed the ancestor was never
        shown an orphaned log — no retraction, no rewind."""
        bc, _ = fresh_node(chains["base"], 8)
        fm = FilterManager(bc)
        fid = fm.new_log_filter(
            LogQuery(from_block=0, to_block=None, addresses=(ADDRS[0],))
        )
        # never polled: cursor sits at from_block-1 = -1 <= ancestor
        fm.note_reorg(5, [self._hit(7, ADDRS[0])])
        assert fm.changes(fid) == []

    def test_block_filter_redelivers_adopted_branch(self, chains):
        bc, driver = fresh_node(chains["base"], 8)
        fm = FilterManager(bc)
        fid = fm.new_block_filter()
        assert fm.changes(fid) == []  # cursor at 8
        mgr = ReorgManager(bc, CFG, driver=driver)
        mgr.add_listener(fm.note_reorg)
        mgr.switch(5, chains["fork"][5:])
        assert fm.changes(fid) == [
            b.hash for b in chains["fork"][5:]
        ]
        assert fm.reorgs_seen == 1

    def test_rpc_rendering_carries_removed_flag(self):
        from khipu_tpu.jsonrpc.eth_service import EthService

        out = EthService._log_json(self._hit(7, ADDRS[0]))
        assert out["removed"] is True
        fresh = EthService._log_json(self._hit(7, ADDRS[0],
                                               removed=False))
        assert fresh["removed"] is False


# ------------------------------------------------------ watchdog storm


class TestReorgStorm:
    def test_storm_trips_once_per_burst(self, chains):
        from khipu_tpu.config import TelemetryConfig
        from khipu_tpu.observability.telemetry import Watchdog

        count = [0]
        clock = [100.0]
        wd = Watchdog(
            config=TelemetryConfig(
                enabled=True, reorg_storm_count=3,
                reorg_storm_window_s=60.0,
            ),
            pipeline={}, clock=lambda: clock[0],
            reorg=lambda: count[0],
        )
        assert "reorg_storm" not in wd.check_once()
        for _ in range(3):  # 3 switches inside the window
            count[0] += 1
            clock[0] += 5.0
            tripped = wd.check_once()
        assert "reorg_storm" in tripped
        # edge-triggered: the standing burst does not re-trip
        clock[0] += 1.0
        assert "reorg_storm" not in wd.check_once()
        assert wd.trips["reorg_storm"] == 1


# ------------------------------------------------- 120-seed chaos sweep


SITES = ["reorg.intent", "reorg.rollback", "reorg.adopt",
         "reorg.finalize"]


class TestReorgSeedSweep:
    def test_120_seeds_land_on_exactly_old_or_new(self, chains):
        """Every ``reorg.*`` seam, staggered depths. After recovery
        the node is at EXACTLY the old chain or the new one — tip hash
        AND state root bit-exact vs the fresh-replay reference — and a
        node left on the old chain re-switches cleanly."""
        base = chains["sweep_base"]      # 6 blocks, MINER_A
        fork = chains["sweep_fork"]      # 8 blocks, diverges at 3
        old_tip = (6, base[5].hash,
                   chains["sweep_base_bc"].get_header_by_number(6)
                   .state_root)
        new_tip = (8, fork[7].hash,
                   chains["sweep_fork_bc"].get_header_by_number(8)
                   .state_root)
        killed = survived = 0
        for seed in range(120):
            site = SITES[seed % len(SITES)]
            after = (seed // len(SITES)) % 6
            bc, driver = fresh_node(base, 6)
            mgr = ReorgManager(bc, CFG, driver=driver)
            plan = FaultPlan(seed=seed, rules=[
                FaultRule(site, "die", times=1, after=after)
            ])
            died = False
            try:
                with active(plan):
                    mgr.switch(3, fork[3:])
            except InjectedDeath:
                died = True
            if died:
                killed += 1
                recover(bc, config=CFG)
            else:
                survived += 1
            best = bc.best_block_number
            tip = bc.get_hash_by_number(best)
            root = bc.get_header_by_number(best).state_root
            assert (best, tip, root) in (old_tip, new_tip), (
                f"seed {seed} ({site} after={after}): neither chain"
            )
            if not died:
                assert (best, tip, root) == new_tip
            assert bc.storages.window_journal.pending() == []
            if (best, tip, root) == old_tip:
                # an abandoned switch must not poison the next attempt
                mgr.switch(3, fork[3:])
                assert bc.best_block_number == 8
                assert bc.get_hash_by_number(8) == fork[7].hash
        assert killed > 20 and survived > 20, (killed, survived)


# ------------------------------------------------- live-load acceptance


class TestLiveLoadAcceptance:
    def test_serving_through_reorg_with_kill_and_recover(self, chains):
        """A reader polling MINER_A's balance through a ReadView while
        a >= 3-block reorg runs — including one mid-adopt death and
        in-process recovery — only ever sees the old tip's value or
        the fork-point/new-chain value, ends on the new chain's value,
        and every orphaned-only tx is pool-resident or re-mined."""
        bc, driver = fresh_node(chains["base"], 8)
        pool = PendingTransactionsPool()
        view = ReadView(bc)
        mgr = ReorgManager(bc, CFG, driver=driver, txpool=pool,
                           read_view=view)
        old_val = _balance(chains["base_bc"], MINER_A, 8)
        anc_val = _balance(chains["base_bc"], MINER_A, 5)
        new_val = _balance(chains["fork_bc"], MINER_A, 10)
        assert old_val > anc_val  # MINER_A really earns on the base
        assert new_val == anc_val  # fork suffix is MINER_B's

        seen, errors, stop = [], [], threading.Event()

        def poll():
            while not stop.is_set():
                try:
                    _num, acct = view.get_account(MINER_A)
                    seen.append(0 if acct is None else acct.balance)
                except Exception as e:  # a crash IS a violation
                    errors.append(repr(e))
                    return

        t = threading.Thread(target=poll, daemon=True)
        t.start()
        try:
            plan = FaultPlan(seed=42, rules=[
                FaultRule("reorg.adopt", "die", times=1, after=2)
            ])
            with pytest.raises(InjectedDeath):
                with active(plan):
                    mgr.switch(5, chains["fork"][5:])
            recover(bc, config=CFG, txpool=pool)
        finally:
            stop.set()
            t.join(timeout=10)
        assert not errors, errors
        assert seen, "reader never completed a poll"
        legal = {old_val, anc_val}
        assert set(seen) <= legal, sorted(set(seen) - legal)
        _num, acct = view.get_account(MINER_A)
        assert acct.balance == new_val
        assert bc.best_block_number == 10
        assert (bc.get_header_by_number(10).state_root
                == chains["fork_bc"].get_header_by_number(10).state_root)
        adopted_hashes = {
            tx.hash for b in chains["fork"][5:]
            for tx in b.body.transactions
        }
        for b in chains["base"][5:]:
            for tx in b.body.transactions:
                assert (tx.hash in adopted_hashes
                        or pool.get(tx.hash) is not None), (
                    "orphaned tx neither re-mined nor pool-resident"
                )
