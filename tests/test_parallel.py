"""Multi-device sharding tests on the virtual 8-CPU mesh.

The sharded digests must equal the scalar host oracle bit-for-bit —
the same contract the single-chip kernels are held to."""

import numpy as np
import pytest

import jax

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.parallel import (
    device_mesh,
    hash_level_all_gather,
    keccak256_fixed_sharded,
    snapshot_verify_sharded,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device CPU mesh"
)


def _rand_nodes(n, length, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(n, length), dtype=np.uint8)


def test_sharded_fixed_matches_oracle():
    mesh = device_mesh(8)
    data = _rand_nodes(40, 100)  # 40 % 8 == 0
    out = keccak256_fixed_sharded(data, mesh)
    for i in range(40):
        assert out[i].tobytes() == keccak256(data[i].tobytes())


def test_sharded_uneven_batch_padded():
    mesh = device_mesh(8)
    data = _rand_nodes(13, 576, seed=1)  # not divisible by 8
    out = keccak256_fixed_sharded(data, mesh)
    assert out.shape == (13, 32)
    for i in range(13):
        assert out[i].tobytes() == keccak256(data[i].tobytes())


def test_sharded_on_smaller_mesh():
    mesh = device_mesh(4)
    data = _rand_nodes(8, 140, seed=2)  # 2-block messages
    out = keccak256_fixed_sharded(data, mesh)
    for i in range(8):
        assert out[i].tobytes() == keccak256(data[i].tobytes())


def test_level_all_gather_replicates_full_table():
    mesh = device_mesh(8)
    data = _rand_nodes(16, 64, seed=3)
    table = hash_level_all_gather(data, mesh)
    assert table.shape == (16, 32)
    for i in range(16):
        assert table[i].tobytes() == keccak256(data[i].tobytes())


def test_snapshot_verify_counts_mismatches():
    mesh = device_mesh(8)
    data = _rand_nodes(24, 200, seed=4)
    keys = np.stack(
        [
            np.frombuffer(keccak256(data[i].tobytes()), dtype=np.uint8)
            for i in range(24)
        ]
    )
    assert snapshot_verify_sharded(data, keys, mesh) == 0
    # corrupt two claimed keys -> exactly 2 global mismatches via psum
    bad = keys.copy()
    bad[3, 0] ^= 0xFF
    bad[17, 31] ^= 0x01
    assert snapshot_verify_sharded(data, bad, mesh) == 2


def test_snapshot_verify_uneven_batch():
    mesh = device_mesh(8)
    data = _rand_nodes(11, 96, seed=5)
    keys = np.stack(
        [
            np.frombuffer(keccak256(data[i].tobytes()), dtype=np.uint8)
            for i in range(11)
        ]
    )
    assert snapshot_verify_sharded(data, keys, mesh) == 0
    keys[10] ^= 0xA5
    assert snapshot_verify_sharded(data, keys, mesh) == 1
