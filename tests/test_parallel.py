"""Multi-device sharding tests on the virtual 8-CPU mesh.

The sharded digests must equal the scalar host oracle bit-for-bit —
the same contract the single-chip kernels are held to."""

import numpy as np
import pytest

import jax

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.parallel import (
    device_mesh,
    hash_level_all_gather,
    keccak256_fixed_sharded,
    snapshot_verify_sharded,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device CPU mesh"
)


def _rand_nodes(n, length, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(n, length), dtype=np.uint8)


def test_sharded_fixed_matches_oracle():
    mesh = device_mesh(8)
    data = _rand_nodes(40, 100)  # 40 % 8 == 0
    out = keccak256_fixed_sharded(data, mesh)
    for i in range(40):
        assert out[i].tobytes() == keccak256(data[i].tobytes())


def test_sharded_uneven_batch_padded():
    mesh = device_mesh(8)
    data = _rand_nodes(13, 576, seed=1)  # not divisible by 8
    out = keccak256_fixed_sharded(data, mesh)
    assert out.shape == (13, 32)
    for i in range(13):
        assert out[i].tobytes() == keccak256(data[i].tobytes())


def test_sharded_on_smaller_mesh():
    mesh = device_mesh(4)
    data = _rand_nodes(8, 140, seed=2)  # 2-block messages
    out = keccak256_fixed_sharded(data, mesh)
    for i in range(8):
        assert out[i].tobytes() == keccak256(data[i].tobytes())


def test_level_all_gather_replicates_full_table():
    mesh = device_mesh(8)
    data = _rand_nodes(16, 64, seed=3)
    table = hash_level_all_gather(data, mesh)
    assert table.shape == (16, 32)
    for i in range(16):
        assert table[i].tobytes() == keccak256(data[i].tobytes())


def test_snapshot_verify_counts_mismatches():
    mesh = device_mesh(8)
    data = _rand_nodes(24, 200, seed=4)
    keys = np.stack(
        [
            np.frombuffer(keccak256(data[i].tobytes()), dtype=np.uint8)
            for i in range(24)
        ]
    )
    assert snapshot_verify_sharded(data, keys, mesh) == 0
    # corrupt two claimed keys -> exactly 2 global mismatches via psum
    bad = keys.copy()
    bad[3, 0] ^= 0xFF
    bad[17, 31] ^= 0x01
    assert snapshot_verify_sharded(data, bad, mesh) == 2


def test_snapshot_verify_uneven_batch():
    mesh = device_mesh(8)
    data = _rand_nodes(11, 96, seed=5)
    keys = np.stack(
        [
            np.frombuffer(keccak256(data[i].tobytes()), dtype=np.uint8)
            for i in range(11)
        ]
    )
    assert snapshot_verify_sharded(data, keys, mesh) == 0
    keys[10] ^= 0xA5
    assert snapshot_verify_sharded(data, keys, mesh) == 1


class TestFusedSharded:
    def test_sharded_fused_resolve_matches_host_finalize(self):
        """The mesh form of the one-dispatch window finalize: identical
        placeholder->hash resolution to the host level loop, with rows
        sharded over 8 devices and digests all_gathered per round."""
        import random

        from khipu_tpu.parallel.fused_sharded import fused_resolve_sharded
        from khipu_tpu.parallel.mesh import device_mesh
        from khipu_tpu.storage.datasource import MemoryNodeDataSource
        from khipu_tpu.trie.bulk import host_hasher
        from khipu_tpu.trie.deferred import (
            _PLACEHOLDER_PREFIX,
            DeferredMPT,
            finalize,
        )
        from khipu_tpu.trie.mpt import MerklePatriciaTrie

        rng = random.Random(77)
        src = MemoryNodeDataSource()
        base = MerklePatriciaTrie(src)
        keys = [keccak256(rng.randbytes(8)) for _ in range(200)]
        for k in keys:
            base = base.put(k, rng.randbytes(rng.randrange(1, 90)))
        base = base.persist()

        def session():
            d = DeferredMPT(
                base.source,
                _root_ref=base._root_ref,
                _logs={h: [c, e] for h, (c, e) in base._logs.items()},
                _staged=dict(base._staged),
            )
            for k in rng.sample(keys, 30):
                d = d.remove(k)
            for _ in range(150):
                d = d.put(keccak256(rng.randbytes(8)), rng.randbytes(40))
            return d

        state = rng.getstate()
        loop_trie, loop_map = finalize(
            session(), host_hasher, return_mapping=True
        )
        rng.setstate(state)  # identical session for the sharded run
        from khipu_tpu.trie.deferred import resolution_inputs

        to_resolve, deps, _ = resolution_inputs(session())
        mesh = device_mesh(8)
        sharded_map = fused_resolve_sharded(
            to_resolve, deps, _PLACEHOLDER_PREFIX, mesh
        )
        assert sharded_map == loop_map
        # and the digests are true content addresses
        from khipu_tpu.trie.deferred import _substitute_bytes

        for ph, enc in to_resolve.items():
            final = _substitute_bytes(enc, sharded_map)
            assert keccak256(final) == sharded_map[ph]
