"""Network stack tests over loopback: ECIES, RLPx handshake/framing,
snappy, full peer connections serving chain data, Kademlia discovery
(parity targets SURVEY §2.7 RLPx stack, HostService, discovery)."""

import time

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.network import snappy_codec
from khipu_tpu.network.ecies import EciesError, decrypt, encrypt
from khipu_tpu.network.rlpx import (
    AuthHandshake,
    FrameCodec,
    _IncrementalKeccak,
)
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder

PRIV_A = (11).to_bytes(32, "big")
PRIV_B = (22).to_bytes(32, "big")
PUB_A = privkey_to_pubkey(PRIV_A)
PUB_B = privkey_to_pubkey(PRIV_B)


class TestEcies:
    def test_roundtrip(self):
        msg = b"rlpx auth payload" * 3
        ct = encrypt(PUB_B, msg, shared_mac_data=b"\x01\x02")
        assert decrypt(PRIV_B, ct, shared_mac_data=b"\x01\x02") == msg

    def test_tamper_and_wrong_key_rejected(self):
        ct = encrypt(PUB_B, b"secret")
        bad = ct[:-1] + bytes([ct[-1] ^ 1])
        with pytest.raises(EciesError):
            decrypt(PRIV_B, bad)
        with pytest.raises(EciesError):
            decrypt(PRIV_A, ct)
        with pytest.raises(EciesError):
            decrypt(PRIV_B, ct, shared_mac_data=b"x")


class TestSnappy:
    def test_roundtrip(self):
        for payload in (b"", b"a", b"hello" * 100, bytes(range(256)) * 7):
            assert snappy_codec.decompress(
                snappy_codec.compress(payload)
            ) == payload

    def test_decodes_copy_tags(self):
        # hand-built stream: literal "abcd" + 1-byte-offset copy of 4
        # back-referencing "abcd" => "abcdabcd"
        stream = bytes([8]) + bytes([(4 - 1) << 2]) + b"abcd" + bytes(
            [(0 << 5) | ((4 - 4) << 2) | 1, 4]
        )
        assert snappy_codec.decompress(stream) == b"abcdabcd"

    def test_overlapping_copy(self):
        # literal "ab" + copy(offset=2, len=6) => "abababab"
        stream = bytes([8, (2 - 1) << 2]) + b"ab" + bytes(
            [((6 - 4) << 2) | 1, 2]
        )
        assert snappy_codec.decompress(stream) == b"abababab"

    def test_bad_streams_rejected(self):
        with pytest.raises(snappy_codec.SnappyError):
            snappy_codec.decompress(b"")
        with pytest.raises(snappy_codec.SnappyError):
            # declared 100 bytes, provides none
            snappy_codec.decompress(bytes([100]))
        with pytest.raises(snappy_codec.SnappyError):
            # copy before any output
            snappy_codec.decompress(bytes([4, 0b101, 1]))


class TestIncrementalKeccak:
    def test_matches_oneshot_and_continues(self):
        k = _IncrementalKeccak()
        k.update(b"hello ")
        k.update(b"world")
        assert k.digest() == keccak256(b"hello world")
        # stream continues after digest snapshot
        k.update(b"!")
        assert k.digest() == keccak256(b"hello world!")

    def test_block_boundaries(self):
        k = _IncrementalKeccak()
        blob = bytes(range(256)) * 3  # > 5 rate blocks
        for i in range(0, len(blob), 37):
            k.update(blob[i : i + 37])
        assert k.digest() == keccak256(blob)


class TestRlpxHandshake:
    def _pair(self):
        initiator = AuthHandshake(PRIV_A)
        responder = AuthHandshake(PRIV_B)
        auth = initiator.create_auth(PUB_B)
        remote_pub = responder.handle_auth(auth)
        assert remote_pub == PUB_A
        ack, resp_secrets = responder.create_ack(remote_pub)
        init_secrets = initiator.handle_ack(ack)
        return init_secrets, resp_secrets

    def test_secrets_agree(self):
        a, b = self._pair()
        assert a.aes == b.aes
        assert a.mac == b.mac
        assert a.egress_mac.digest() == b.ingress_mac.digest()
        assert a.ingress_mac.digest() == b.egress_mac.digest()

    def test_frames_roundtrip_both_directions(self):
        a, b = self._pair()
        ca, cb = FrameCodec(a), FrameCodec(b)
        for i, msg in enumerate(
            [b"\x80", b"ping", b"x" * 15, b"y" * 16, b"z" * 1000]
        ):
            wire = ca.write_frame(msg)
            size = cb.read_header(wire[:32])
            assert cb.read_frame(size, wire[32:]) == msg
            back = cb.write_frame(msg + b"-reply")
            size = ca.read_header(back[:32])
            assert ca.read_frame(size, back[32:]) == msg + b"-reply"

    def test_tampered_frame_rejected(self):
        a, b = self._pair()
        ca, cb = FrameCodec(a), FrameCodec(b)
        wire = bytearray(ca.write_frame(b"payload"))
        wire[40] ^= 1  # flip a ciphertext byte
        size = cb.read_header(bytes(wire[:32]))
        with pytest.raises(ValueError, match="MAC"):
            cb.read_frame(size, bytes(wire[32:]))


CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(3)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]


def make_chain(n_blocks=3):
    bc = Blockchain(Storages(), CFG)
    builder = ChainBuilder(
        bc, CFG, GenesisSpec(alloc={a: 10**21 for a in ADDRS})
    )
    for n in range(n_blocks):
        builder.add_block(
            [sign_transaction(
                Transaction(n, 10**9, 21000, ADDRS[1], 5), KEYS[0], chain_id=1
            )],
            coinbase=b"\xaa" * 20,
        )
    return bc


class TestPeerStack:
    def test_full_stack_serves_chain_data(self):
        from khipu_tpu.network.host_service import HostService
        from khipu_tpu.network.messages import (
            BLOCK_BODIES,
            BLOCK_HEADERS,
            ETH_OFFSET,
            GET_BLOCK_BODIES,
            GET_BLOCK_HEADERS,
            GET_NODE_DATA,
            NODE_DATA,
            GetBlockHeaders,
            Status,
            decode_headers,
        )
        from khipu_tpu.network.peer import PeerManager

        bc = make_chain()

        def status():
            best = bc.best_block_number
            return Status(
                63, 1,
                bc.get_total_difficulty(best) or 0,
                bc.get_header_by_number(best).hash,
                bc.get_header_by_number(0).hash,
            )

        server = PeerManager(PRIV_B, "khipu-tpu/server", status)
        HostService(bc).install(server)
        port = server.listen()

        client = PeerManager(PRIV_A, "khipu-tpu/client", status)
        try:
            peer = client.connect("127.0.0.1", port, PUB_B)
            assert peer.hello.client_id == "khipu-tpu/server"
            assert peer.status.total_difficulty == status().total_difficulty
            assert peer.snappy  # p2p v5 both sides

            # headers by number range
            body = peer.request(
                ETH_OFFSET + GET_BLOCK_HEADERS,
                GetBlockHeaders(1, max_headers=3).body(),
                ETH_OFFSET + BLOCK_HEADERS,
            )
            headers = decode_headers(body)
            assert [h.number for h in headers] == [1, 2, 3]
            assert headers[2].hash == bc.get_header_by_number(3).hash

            # bodies by hash
            bodies = peer.request(
                ETH_OFFSET + GET_BLOCK_BODIES,
                [headers[0].hash],
                ETH_OFFSET + BLOCK_BODIES,
            )
            assert len(bodies) == 1

            # node data by hash (fast-sync supplier path)
            root = bc.get_header_by_number(3).state_root
            nodes = peer.request(
                ETH_OFFSET + GET_NODE_DATA, [root], ETH_OFFSET + NODE_DATA
            )
            assert len(nodes) == 1
            assert keccak256(nodes[0]) == root
        finally:
            client.stop()
            server.stop()

    def test_genesis_mismatch_rejected(self):
        from khipu_tpu.network.messages import Status
        from khipu_tpu.network.peer import PeerError, PeerManager

        bc = make_chain(1)

        def status_a():
            return Status(63, 1, 1, b"\x01" * 32, b"\xaa" * 32)

        def status_b():
            return Status(63, 1, 1, b"\x01" * 32, b"\xbb" * 32)

        server = PeerManager(PRIV_B, "s", status_b)
        port = server.listen()
        client = PeerManager(PRIV_A, "c", status_a)
        try:
            with pytest.raises(PeerError, match="genesis"):
                client.connect("127.0.0.1", port, PUB_B)
        finally:
            client.stop()
            server.stop()


class TestDiscovery:
    def test_three_node_bootstrap(self):
        from khipu_tpu.network.discovery import DiscoveryService

        a = DiscoveryService((31).to_bytes(32, "big"))
        b = DiscoveryService((32).to_bytes(32, "big"))
        c = DiscoveryService((33).to_bytes(32, "big"))
        for s in (a, b, c):
            s.start()
        try:
            # b and c know each other; a bootstraps from b only
            b.table.add(c.record)
            found = a.bootstrap([b.record], timeout=2.0)
            assert found >= 2  # learned b via pong and c via neighbours
            pubs = {
                r.pubkey
                for bucket in a.table.buckets
                for r in bucket
            }
            assert b.pubkey in pubs and c.pubkey in pubs
        finally:
            for s in (a, b, c):
                s.stop()

    def test_packet_codec_and_tamper(self):
        from khipu_tpu.network.discovery import (
            decode_packet,
            encode_packet,
        )

        packet = encode_packet(PRIV_A, 1, [b"x"])
        pub, ptype, body = decode_packet(packet)
        assert pub == PUB_A and ptype == 1 and body == [b"x"]
        bad = packet[:40] + bytes([packet[40] ^ 1]) + packet[41:]
        with pytest.raises(ValueError):
            decode_packet(bad)

    def test_routing_table_eviction(self):
        from khipu_tpu.network.discovery import (
            K_BUCKET,
            KRoutingTable,
            NodeRecord,
        )

        table = KRoutingTable(PUB_A)
        for i in range(3 * K_BUCKET):
            table.add(
                NodeRecord(
                    privkey_to_pubkey((100 + i).to_bytes(32, "big")),
                    "127.0.0.1", 30000 + i, 30000 + i,
                )
            )
        assert all(len(b) <= K_BUCKET for b in table.buckets)
        closest = table.closest(keccak256(PUB_A), k=5)
        assert len(closest) == 5


class TestSnappyCompressor:
    """The C greedy compressor (rlp_ext.snappy_compress) must round-trip
    through our spec decompressor and actually compress; the all-literal
    fallback stays valid."""

    def test_roundtrip_and_ratio(self):
        import random

        from khipu_tpu.network.snappy_codec import (
            _compress_literal,
            compress,
            decompress,
        )

        rng = random.Random(9)
        cases = [
            b"", b"a", b"ab" * 3, b"x" * 100, b"hello world " * 500,
            rng.randbytes(1000),
            bytes(70000),
            (b"hdr" + bytes(40)) * 2000,
            rng.randbytes(200) * 300,
        ]
        for c in cases:
            assert decompress(compress(c), max_len=1 << 26) == c
            assert decompress(_compress_literal(c), max_len=1 << 26) == c
        for _ in range(100):
            blob = bytes(
                rng.choice(b"abcd") for _ in range(rng.randint(0, 3000))
            )
            assert decompress(compress(blob), max_len=1 << 26) == blob
        big = (b"repetitive-node-payload" + bytes(32)) * 5000
        z = compress(big)
        assert decompress(z, max_len=1 << 26) == big
        from khipu_tpu.native.build import load_rlp_ext

        if load_rlp_ext() is not None:
            assert len(z) < len(big) // 5, "compressor not compressing"

    def test_expansion_worst_case_no_overflow(self):
        """Regression: greedy emission can EXPAND (short literal runs +
        4-byte copies); the C buffer must use the snappy worst-case
        bound, not a per-64KiB slack — this shape overflowed a 4-bytes-
        per-region capacity and segfaulted."""
        import random

        from khipu_tpu.network.snappy_codec import compress, decompress

        rng = random.Random(1)
        parts = []
        for i in range(8000):
            parts.append(rng.randbytes(59))
            parts.append(i.to_bytes(2, "big"))
            parts.append(b"MARK")
        blob = b"".join(parts)
        assert decompress(compress(blob), max_len=1 << 26) == blob
