"""MPT correctness: golden vectors, fuzz vs bulk builder, genesis root.

The mainnet genesis state root / block hash constants below are public
chain facts (any Ethereum client computes them), giving an external
bit-exactness oracle per SURVEY.md §4 item (3).
"""

import gzip
import os
import random

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.rlp import rlp_encode
from khipu_tpu.trie import EMPTY_TRIE_HASH, MerklePatriciaTrie, bulk_build
from khipu_tpu.trie.bulk import host_hasher

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")

MAINNET_GENESIS_STATE_ROOT = bytes.fromhex(
    "d7f8974fb5ac78d9ac099b9ad5018bedc2ce0a72dad1827a1709da30580f0544"
)


class DictSource:
    def __init__(self):
        self.d = {}

    def get(self, k):
        return self.d.get(k)

    def put(self, k, v):
        self.d[k] = v

    def update(self, to_remove, to_upsert):
        self.d.update(to_upsert)


def fresh():
    return MerklePatriciaTrie(DictSource())


def test_empty_trie_hash():
    assert EMPTY_TRIE_HASH.hex() == (
        "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
    )
    assert fresh().root_hash == EMPTY_TRIE_HASH


def test_known_vector_dogs():
    # Canonical MPT example (appears in the yellow-paper literature).
    pairs = {
        b"do": b"verb",
        b"dog": b"puppy",
        b"doge": b"coin",
        b"horse": b"stallion",
    }
    t = fresh()
    for k, v in pairs.items():
        t = t.put(k, v)
    assert t.root_hash.hex() == (
        "5991bb8c6514148a29db676a14ac506cd2cd5775ace63c30a4fe457715e9ac84"
    )
    for k, v in pairs.items():
        assert t.get(k) == v
    assert t.get(b"dogs") is None
    # insertion order must not matter
    t2 = fresh()
    for k in reversed(list(pairs)):
        t2 = t2.put(k, pairs[k])
    assert t2.root_hash == t.root_hash
    # bulk builder agrees
    root, _ = bulk_build(pairs.items())
    assert root == t.root_hash


def test_single_entry_and_overwrite():
    t = fresh().put(b"k", b"v1")
    r1 = t.root_hash
    t = t.put(b"k", b"v2")
    assert t.get(b"k") == b"v2"
    t = t.put(b"k", b"v1")
    assert t.root_hash == r1


def test_remove_returns_to_prior_root():
    t = fresh()
    t = t.put(b"alpha", b"1")
    r1 = t.root_hash
    t = t.put(b"alphabet", b"2").put(b"beta", b"3")
    t = t.remove(b"alphabet").remove(b"beta")
    assert t.root_hash == r1
    t = t.remove(b"alpha")
    assert t.root_hash == EMPTY_TRIE_HASH


def test_branch_value_slot():
    # One key a strict prefix of another → branch with terminator value.
    t = fresh().put(b"ab", b"outer").put(b"abcd", b"inner")
    assert t.get(b"ab") == b"outer"
    assert t.get(b"abcd") == b"inner"
    t2 = t.remove(b"ab")
    assert t2.get(b"ab") is None
    assert t2.get(b"abcd") == b"inner"
    assert t2.root_hash == fresh().put(b"abcd", b"inner").root_hash


def test_persist_and_reopen():
    src = DictSource()
    t = MerklePatriciaTrie(src)
    data = {bytes([i, i ^ 0x5A]) * 4: b"value-%d" % i for i in range(64)}
    for k, v in data.items():
        t = t.put(k, v)
    root = t.root_hash
    t = t.persist()
    reopened = MerklePatriciaTrie(src, root_hash=root)
    for k, v in data.items():
        assert reopened.get(k) == v
    # mutate the reopened trie across persisted boundary
    reopened = reopened.put(b"new-key", b"new-value").persist()
    again = MerklePatriciaTrie(src, root_hash=reopened.root_hash)
    assert again.get(b"new-key") == b"new-value"


@pytest.mark.parametrize("seed", [1, 7, 2026])
def test_fuzz_incremental_vs_bulk(seed):
    rng = random.Random(seed)
    n = 300
    pairs = {}
    for _ in range(n):
        klen = rng.randint(1, 48)
        pairs[rng.randbytes(klen)] = rng.randbytes(rng.randint(1, 80))
    t = fresh()
    keys = list(pairs)
    rng.shuffle(keys)
    for k in keys:
        t = t.put(k, pairs[k])
    bulk_root, nodes = bulk_build(pairs.items(), hasher=host_hasher)
    assert t.root_hash == bulk_root
    # node sets persisted by the incremental path == bulk path
    _, upserts = t.changes()
    assert set(upserts) == set(nodes)

    # remove a random half; incremental root must equal bulk of remainder
    removed = set(rng.sample(keys, n // 2))
    for k in removed:
        t = t.remove(k)
    remaining = {k: v for k, v in pairs.items() if k not in removed}
    assert t.root_hash == bulk_build(remaining.items())[0]
    for k in removed:
        assert t.get(k) is None
    for k, v in remaining.items():
        assert t.get(k) == v


def test_secure_trie_style_keys():
    # State-trie usage: key = keccak256(address), value = rlp(account).
    rng = random.Random(99)
    pairs = {}
    for i in range(200):
        addr = rng.randbytes(20)
        account = [
            rlp_int(0),
            rlp_int(rng.randint(1, 10**20)),
            EMPTY_TRIE_HASH,
            keccak256(b""),
        ]
        pairs[keccak256(addr)] = rlp_encode(account)
    t = fresh()
    for k, v in pairs.items():
        t = t.put(k, v)
    assert t.root_hash == bulk_build(pairs.items())[0]


def rlp_int(v: int) -> bytes:
    """Minimal big-endian scalar — an RLP list *item*, not an encoded
    RLP string (so NOT rlp_encode_int, which adds the length prefix)."""
    from khipu_tpu.base.rlp import int_to_big_endian

    return int_to_big_endian(v)


def genesis_alloc():
    path = os.path.join(FIXTURES, "mainnet_genesis_alloc.txt.gz")
    with gzip.open(path, "rt") as f:
        for line in f:
            addr, bal = line.split()
            yield bytes.fromhex(addr), int(bal)


def genesis_state_pairs():
    empty_code_hash = keccak256(b"")
    for addr, bal in genesis_alloc():
        account = [rlp_int(0), rlp_int(bal), EMPTY_TRIE_HASH, empty_code_hash]
        yield keccak256(addr), rlp_encode(account)


def test_mainnet_genesis_state_root_bulk():
    """8893-account mainnet genesis alloc → the exact geth state root."""
    root, nodes = bulk_build(genesis_state_pairs(), hasher=host_hasher)
    assert root == MAINNET_GENESIS_STATE_ROOT
    assert len(nodes) > 8893  # every account leaf hashes to >=32B


def test_mainnet_genesis_state_root_incremental_subset():
    """Incremental trie agrees with bulk on a 500-account prefix."""
    pairs = []
    for i, kv in enumerate(genesis_state_pairs()):
        if i >= 500:
            break
        pairs.append(kv)
    t = fresh()
    for k, v in pairs:
        t = t.put(k, v)
    assert t.root_hash == bulk_build(pairs)[0]


def test_hash_aliased_nodes_survive_removal():
    """Two identical leaves alias one hash; removing one referent must
    not drop the other's node from the persisted set (refcounted log)."""
    src = DictSource()
    t = MerklePatriciaTrie(src)
    k1, k2, k3 = b"\x10" + b"\xaa" * 4, b"\x20" + b"\xaa" * 4, b"\x31" * 5
    t = t.put(k1, b"V" * 40).put(k2, b"V" * 40).put(k3, b"W" * 40)
    t = t.remove(k1)
    root = t.root_hash
    t.persist()
    reopened = MerklePatriciaTrie(src, root_hash=root)
    assert reopened.get(k2) == b"V" * 40  # was MPTNodeMissingException
    assert reopened.get(k3) == b"W" * 40
    assert reopened.get(k1) is None


def test_empty_trie_hash_literal():
    assert EMPTY_TRIE_HASH == keccak256(rlp_encode(b""))


@pytest.mark.parametrize("seed", [2, 11])
def test_fused_bulk_equals_level_loop(seed):
    """The one-dispatch fused bulk resolve (trie/bulk._resolve_fused)
    is bit-exact with the per-level hasher loop: same root, same
    content-addressed node set — including inline (<32 B) capping and
    embedded-child substitution."""
    rng = random.Random(seed)
    pairs = {
        rng.randbytes(rng.randint(1, 40)): rng.randbytes(rng.randint(1, 90))
        for _ in range(1500)
    }
    r1, n1 = bulk_build(pairs.items(), hasher=host_hasher)
    r2, n2 = bulk_build(pairs.items(), fused=True)
    assert r1 == r2
    assert n1 == n2
    # tiny tries incl. inline-root edge
    for k in (1, 2, 3, 9):
        sub = dict(list(pairs.items())[:k])
        assert bulk_build(sub.items(), fused=True) == bulk_build(
            sub.items(), hasher=host_hasher
        )
