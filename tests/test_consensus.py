"""Difficulty calculator + Ethash tests (parity targets
DifficultyCalculator.scala:17, EthashAlgo.scala:49). Ethash runs with
reduced sizes in CI (the algorithm is size-generic, like the
reference's EthashParams); the closed mine -> validate loop plus
tamper-rejection pins the structure."""

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.config import BlockchainConfig, fixture_config
from khipu_tpu.consensus.ethash import (
    EthashCache,
    cache_size,
    check_pow,
    dataset_size,
    hashimoto_light,
    mine,
    seed_hash,
)
from khipu_tpu.domain.block_header import EMPTY_OMMERS_HASH, BlockHeader
from khipu_tpu.domain.difficulty import MIN_DIFFICULTY, calc_difficulty


def header(number, difficulty, ts, ommers=EMPTY_OMMERS_HASH):
    return BlockHeader(
        parent_hash=b"\x00" * 32,
        ommers_hash=ommers,
        beneficiary=b"\x00" * 20,
        state_root=b"\x00" * 32,
        transactions_root=b"\x00" * 32,
        receipts_root=b"\x00" * 32,
        logs_bloom=b"\x00" * 256,
        difficulty=difficulty,
        number=number,
        gas_limit=8_000_000,
        gas_used=0,
        unix_timestamp=ts,
    )


MAINNET = BlockchainConfig()


class TestDifficulty:
    def test_frontier_up_down(self):
        parent = header(100, 2**20, 1000)
        up = calc_difficulty(1010, parent, MAINNET)  # dt=10 < 13
        down = calc_difficulty(1020, parent, MAINNET)
        adj = 2**20 // 2048
        assert up == 2**20 + adj
        assert down == 2**20 - adj

    def test_homestead_sigma(self):
        parent = header(1_200_000, 2**22, 1000)
        # dt=5 -> sigma 1; dt=25 -> sigma -1; dt very large -> floor -99
        adj = 2**22 // 2048
        bomb = 2 ** (1_200_001 // 100_000 - 2)  # period 12
        assert calc_difficulty(1005, parent, MAINNET) == 2**22 + adj + bomb
        assert calc_difficulty(1025, parent, MAINNET) == 2**22 - adj + bomb
        floor = calc_difficulty(1000 + 10_000, parent, MAINNET)
        assert floor == max(2**22 - 99 * adj, MIN_DIFFICULTY) + bomb

    def test_byzantium_ommer_bonus_and_bomb_rewind(self):
        n = 4_400_000
        parent_plain = header(n, 2**24, 1000)
        parent_ommer = header(n, 2**24, 1000, ommers=b"\x11" * 32)
        d_plain = calc_difficulty(1006, parent_plain, MAINNET)
        d_ommer = calc_difficulty(1006, parent_ommer, MAINNET)
        adj = 2**24 // 2048
        assert d_ommer - d_plain == adj  # sigma 2 vs 1
        # bomb rewound by 3M: fake period (4.4M+1-3M)/100k = 14
        assert d_plain == 2**24 + adj * 1 + 2 ** (14 - 2)

    def test_min_difficulty_floor(self):
        parent = header(5, MIN_DIFFICULTY, 0)
        assert calc_difficulty(10**9, parent, MAINNET) == MIN_DIFFICULTY


# CI-budget Ethash: 1024-row cache, 4096-item virtual dataset.
CACHE_BYTES = 1024 * 64
FULL_SIZE = 4096 * 64


@pytest.fixture(scope="module")
def cache():
    return EthashCache(0, cache_bytes=CACHE_BYTES)


class TestEthash:
    def test_seed_chain(self):
        assert seed_hash(0) == b"\x00" * 32
        assert seed_hash(1) == keccak256(b"\x00" * 32)
        assert seed_hash(2) == keccak256(keccak256(b"\x00" * 32))

    def test_spec_sizes_are_prime_multiples(self):
        assert cache_size(0) == 16_776_896
        assert dataset_size(0) == 1_073_739_904

    def test_cache_determinism(self, cache):
        again = EthashCache(0, cache_bytes=CACHE_BYTES)
        assert (cache.cache == again.cache).all()
        other_epoch = EthashCache(1, cache_bytes=CACHE_BYTES)
        assert not (cache.cache == other_epoch.cache).all()

    def test_mine_validate_roundtrip(self, cache):
        h = keccak256(b"header-under-seal")
        difficulty = 16
        nonce, mix = mine(cache, h, difficulty, full_size=FULL_SIZE)
        assert check_pow(cache, h, mix, nonce, difficulty, FULL_SIZE)

    def test_tampered_seal_rejected(self, cache):
        h = keccak256(b"header-under-seal")
        nonce, mix = mine(cache, h, 4, full_size=FULL_SIZE)
        assert not check_pow(cache, h, mix, nonce + 1, 4, FULL_SIZE)
        bad_mix = bytes([mix[0] ^ 1]) + mix[1:]
        assert not check_pow(cache, h, bad_mix, nonce, 4, FULL_SIZE)
        assert not check_pow(
            cache, keccak256(b"other"), mix, nonce, 4, FULL_SIZE
        )

    def test_difficulty_bound_enforced(self, cache):
        h = keccak256(b"x")
        _, result = hashimoto_light(cache, h, 12345, FULL_SIZE)
        # absurd difficulty: the same seal fails the bound check
        nonce, mix = mine(cache, h, 1, full_size=FULL_SIZE)
        assert not check_pow(cache, h, mix, nonce, 1 << 255, FULL_SIZE)

    def test_header_seal_integration(self, cache):
        """BlockHeaderValidator's seal_check hook wired to ethash: a
        genuinely mined header passes, a garbage seal raises."""
        import dataclasses

        from khipu_tpu.validators.validators import (
            BlockHeaderValidator,
            HeaderValidationError,
        )

        def seal_ok(h):
            return check_pow(
                cache,
                keccak256(h.encode_without_nonce()),
                h.mix_hash,
                int.from_bytes(h.nonce, "big"),
                h.difficulty,
                FULL_SIZE,
            )

        parent = header(0, 8, 0)
        base = dataclasses.replace(
            header(1, 8, 13), parent_hash=parent.hash
        )  # declared difficulty 8: minable in CI
        pow_hash = keccak256(base.encode_without_nonce())
        nonce, mix = mine(cache, pow_hash, 8, full_size=FULL_SIZE)
        sealed = dataclasses.replace(
            base, mix_hash=mix, nonce=nonce.to_bytes(8, "big")
        )
        v = BlockHeaderValidator(
            fixture_config().blockchain, seal_check=seal_ok
        )
        v.validate(sealed, parent)  # mined seal accepted
        garbage = dataclasses.replace(base, mix_hash=b"\x00" * 32)
        with pytest.raises(HeaderValidationError):
            v.validate(garbage, parent)


class TestFullDataset:
    """Miner-grade Ethash: precomputed DAG with the on-disk file cache
    (Ethash.scala:65-164,196 role), at a reduced epoch size — the
    algorithm is size-parametric so the code path is the spec path."""

    FULL = 64 * 128  # 8 KiB: 128 items, multiple of MIX_BYTES

    def test_full_equals_light_and_file_cache(self, tmp_path):
        from khipu_tpu.consensus.ethash import (
            EthashCache,
            EthashDataset,
            check_pow,
            hashimoto_full,
            hashimoto_light,
            mine_full,
        )

        cache = EthashCache(0, cache_bytes=1024)
        ds = EthashDataset(cache, self.FULL, cache_dir=str(tmp_path))
        header_hash = b"\x5a" * 32
        # full == light for the same reduced size, several nonces
        for nonce in (0, 1, 77):
            assert hashimoto_full(ds, header_hash, nonce) == (
                hashimoto_light(cache, header_hash, nonce, self.FULL)
            )
        # mine on the DAG, validate on the light path (the real
        # miner/validator split)
        nonce, mix = mine_full(ds, header_hash, difficulty=4)
        assert check_pow(
            cache, header_hash, mix, nonce, 4, full_size=self.FULL
        )
        # second construction memory-maps the cached file (no regen):
        # poke the probe row to prove the spot-check guards corruption
        ds2 = EthashDataset(cache, self.FULL, cache_dir=str(tmp_path))
        assert ds2.path == ds.path
        import numpy as np

        assert np.array_equal(ds2.data, ds.data)

    def test_corrupt_dag_file_regenerates(self, tmp_path):
        import numpy as np

        from khipu_tpu.consensus.ethash import EthashCache, EthashDataset

        cache = EthashCache(0, cache_bytes=1024)
        ds = EthashDataset(cache, self.FULL, cache_dir=str(tmp_path))
        # corrupt the probe row on disk
        arr = np.memmap(ds.path, dtype="<u4", mode="r+")
        arr[arr.shape[0] // 2] ^= 0xDEADBEEF
        n_items = self.FULL // 64
        arr.reshape(n_items, 16)[n_items // 2] ^= 1
        arr.flush()
        del arr
        ds3 = EthashDataset(cache, self.FULL, cache_dir=str(tmp_path))
        probe = n_items // 2
        assert np.array_equal(
            ds3.data[probe], cache.calc_dataset_item(probe)
        )

    def test_batch_generation_equals_scalar(self):
        import numpy as np

        from khipu_tpu.consensus.ethash import EthashCache

        cache = EthashCache(0, cache_bytes=2048)
        idxs = np.array([0, 1, 7, 63, 64, 127], dtype=np.uint64)
        batch = cache.calc_dataset_batch(idxs)
        for k, i in enumerate(idxs):
            assert np.array_equal(
                batch[k], cache.calc_dataset_item(int(i))
            ), i
