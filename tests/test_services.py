"""ServiceBoard / CLI / sqlite engine / remote read-through / tracer
tests (parity targets ServiceBoard.scala:64, Khipu.scala:45, khipu-lmdb
role, DistributedNodeStorage.scala:13, debug-trace-at)."""

import dataclasses
import io
import json
import urllib.request
from contextlib import redirect_stdout

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import DbConfig, SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.service_board import ServiceBoard
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder

KEYS = [(i + 1).to_bytes(32, "big") for i in range(3)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ALLOC = {a: 10**21 for a in ADDRS}


class TestSqliteEngine:
    def test_full_chain_and_restart(self, tmp_path):
        cfg = fixture_config(chain_id=1)
        st = Storages(engine="sqlite", data_dir=str(tmp_path))
        builder = ChainBuilder(
            Blockchain(st, cfg), cfg, GenesisSpec(alloc=ALLOC)
        )
        for n in range(3):
            builder.add_block(
                [sign_transaction(
                    Transaction(n, 10**9, 21000, ADDRS[1], 5), KEYS[0],
                    chain_id=1,
                )],
                coinbase=b"\xaa" * 20,
            )
        head = builder.head
        st.stop()

        st2 = Storages(engine="sqlite", data_dir=str(tmp_path))
        bc2 = Blockchain(st2, fixture_config(chain_id=1))
        assert bc2.best_block_number == 3
        assert bc2.get_header_by_number(3).hash == head.hash
        assert bc2.get_account(
            ADDRS[1], head.header.state_root
        ).balance == 10**21 + 15
        st2.stop()

    def test_kv_remove(self, tmp_path):
        from khipu_tpu.storage.sqlite_engine import SqliteKeyValueDataSource

        src = SqliteKeyValueDataSource(str(tmp_path), "kv")
        src.put(b"a", b"1")
        assert src.get(b"a") == b"1"
        src.remove(b"a")
        assert src.get(b"a") is None
        src.stop()


class TestServiceBoard:
    def test_boot_services_and_shutdown(self, tmp_path):
        cfg = dataclasses.replace(
            fixture_config(chain_id=1),
            db=DbConfig(engine="sqlite", data_dir=str(tmp_path)),
        )
        board = ServiceBoard(cfg, GenesisSpec(alloc=ALLOC))
        assert board.blockchain.best_block_number == 0
        rpc_port = board.start_rpc(port=0)
        bridge_port = board.start_bridge(port=0)
        p2p_port = board.start_network(port=0)

        # RPC answers over HTTP
        req = urllib.request.Request(
            f"http://127.0.0.1:{rpc_port}/",
            data=json.dumps({
                "jsonrpc": "2.0", "id": 1,
                "method": "eth_blockNumber", "params": [],
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        out = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert out["result"] == "0x0"

        # bridge answers over gRPC
        from khipu_tpu.bridge import BridgeClient

        client = BridgeClient(f"127.0.0.1:{bridge_port}")
        assert client.ping(b"x") == b"x"
        client.close()
        assert p2p_port > 0

        # node key persisted with restrictive permissions
        import os
        import stat

        key_path = tmp_path / "nodekey"
        assert key_path.exists()
        assert stat.S_IMODE(os.stat(key_path).st_mode) == 0o600
        first_key = board.node_key
        board.shutdown()

        board2 = ServiceBoard(cfg, GenesisSpec(alloc=ALLOC))
        assert board2.node_key == first_key  # stable identity
        board2.shutdown()

    def test_cli_help(self):
        from khipu_tpu.__main__ import main

        with pytest.raises(SystemExit) as e:
            main(["--help"])
        assert e.value.code == 0


class TestRemoteReadThrough:
    def test_heals_missing_nodes(self):
        from khipu_tpu.storage.remote import RemoteReadThroughNodeStorage

        cfg = fixture_config(chain_id=1)
        src_bc = Blockchain(Storages(), cfg)
        builder = ChainBuilder(src_bc, cfg, GenesisSpec(alloc=ALLOC))
        head = builder.add_block(
            [sign_transaction(
                Transaction(0, 10**9, 21000, ADDRS[1], 5), KEYS[0],
                chain_id=1,
            )],
            coinbase=b"\xaa" * 20,
        )

        def fetch(hashes):
            out = {}
            for h in hashes:
                v = src_bc.storages.account_node_storage.get(h)
                if v is not None:
                    out[h] = v
            return out

        # an EMPTY local store backed by the remote: world reads succeed
        local = Storages()
        healed = RemoteReadThroughNodeStorage(
            local.account_node_storage, fetch
        )
        target = Blockchain(local, cfg)
        target.storages.account_node_storage = healed  # read-through
        from khipu_tpu.trie.mpt import MerklePatriciaTrie

        trie = MerklePatriciaTrie(healed, root_hash=head.header.state_root)
        from khipu_tpu.domain.account import Account, address_key

        raw = trie.get(address_key(ADDRS[1]))
        assert Account.decode(raw).balance == 10**21 + 5
        assert healed.healed > 0
        # healed nodes are now local: a second read needs no remote
        healed.fetch = lambda hashes: (_ for _ in ()).throw(
            AssertionError("remote hit after heal")
        )
        assert trie.get(address_key(ADDRS[1])) == raw  # cache… local

    def test_corrupt_remote_rejected(self):
        from khipu_tpu.storage.remote import RemoteReadThroughNodeStorage

        local = Storages()
        wrapped = RemoteReadThroughNodeStorage(
            local.account_node_storage,
            lambda hashes: {h: b"garbage" for h in hashes},
        )
        assert wrapped.get(keccak256(b"missing")) is None
        assert wrapped.healed == 0


class TestDebugTrace:
    def test_traced_block_prints_opcode_lines(self):
        cfg = dataclasses.replace(
            fixture_config(chain_id=1),
            sync=SyncConfig(parallel_tx=True, debug_trace_at=1),
        )
        builder = ChainBuilder(
            Blockchain(Storages(), cfg), cfg, GenesisSpec(alloc=ALLOC)
        )
        # a contract creation so real opcodes execute
        init = bytes.fromhex("602a600055")
        buf = io.StringIO()
        with redirect_stdout(buf):
            builder.add_block(
                [sign_transaction(
                    Transaction(0, 10**9, 100_000, None, 0, init), KEYS[0],
                    chain_id=1,
                )],
                coinbase=b"\xaa" * 20,
            )
        lines = [l for l in buf.getvalue().splitlines() if l.startswith("[trace]")]
        assert len(lines) >= 3  # PUSH1, PUSH1, SSTORE
        assert any("0x55" in l for l in lines)  # SSTORE traced
        # untraced block: silent
        buf2 = io.StringIO()
        with redirect_stdout(buf2):
            builder.add_block(
                [sign_transaction(
                    Transaction(1, 10**9, 21_000, ADDRS[1], 1), KEYS[0],
                    chain_id=1,
                )],
                coinbase=b"\xaa" * 20,
            )
        assert "[trace]" not in buf2.getvalue()
