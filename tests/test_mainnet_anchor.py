"""REAL mainnet ground truth (non-circular oracles).

The block-1 header below is real Ethereum mainnet data, and the tests
prove it IN-TREE: `test_block1_pow_validates` recomputes the Ethash mix
over the full spec-size epoch-0 cache — a PoW that validates pins every
header byte cryptographically (forging a passing (mixHash, nonce) for
altered fields would require re-mining mainnet block 1), so the header
constants cannot drift into fiction. With the header authenticated,
`test_replay_genesis_to_block1` becomes a true external replay anchor:
genesis alloc -> state trie -> block reward -> state root must equal
the PoW-protected stateRoot, exercising the same consensus gate the
reference faced on live sync (Ledger.scala:603-620).

Parity: consensus/pow/EthashAlgo.scala:143 (hashimoto),
Ethash.scala:301 (validate), ledger/Ledger.scala:603-620.
"""

import gzip
import os

import numpy as np
import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.config import KhipuConfig
from khipu_tpu.consensus.ethash import (
    EthashCache,
    cache_size,
    check_pow,
    seed_hash,
)
from khipu_tpu.domain.block import Block, BlockBody
from khipu_tpu.domain.block_header import BlockHeader
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.difficulty import calc_difficulty
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.replay import ReplayDriver
from khipu_tpu.trie.mpt import EMPTY_TRIE_HASH

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")

# Mainnet genesis (pinned by test_domain/test_trie golden tests).
GENESIS_STATE_ROOT = bytes.fromhex(
    "d7f8974fb5ac78d9ac099b9ad5018bedc2ce0a72dad1827a1709da30580f0544"
)
GENESIS_HASH = bytes.fromhex(
    "d4e56740f876aef8c010b86a40d5f56745a118d0906a34e69aec8c0db1cb8fa3"
)

# Mainnet block 1 — mined 2015-07-30 by 0x05a56e2d... at difficulty
# 17,171,480,576. PoW-authenticated by test_block1_pow_validates.
BLOCK1 = BlockHeader(
    parent_hash=GENESIS_HASH,
    ommers_hash=bytes.fromhex(
        "1dcc4de8dec75d7aab85b567b6ccd41ad312451b948a7413f0a142fd40d49347"
    ),
    beneficiary=bytes.fromhex("05a56e2d52c817161883f50c441c3228cfe54d9f"),
    state_root=bytes.fromhex(
        "d67e4d450343046425ae4271474353857ab860dbc0a1dde64b41b5cd3a532bf3"
    ),
    transactions_root=EMPTY_TRIE_HASH,
    receipts_root=EMPTY_TRIE_HASH,
    logs_bloom=b"\x00" * 256,
    difficulty=17_171_480_576,
    number=1,
    gas_limit=5000,
    gas_used=0,
    unix_timestamp=1_438_269_988,
    extra_data=bytes.fromhex(
        "476574682f76312e302e302f6c696e75782f676f312e342e32"
    ),  # "Geth/v1.0.0/linux/go1.4.2"
    mix_hash=bytes.fromhex(
        "969b900de27b6ac6a67742365dd65f55a0526c41fd18e1b16f1a1215c2e66f59"
    ),
    nonce=bytes.fromhex("539bd4979fef1ec4"),
)


def mainnet_genesis_spec() -> GenesisSpec:
    alloc = {}
    with gzip.open(
        os.path.join(FIXTURES, "mainnet_genesis_alloc.txt.gz"), "rt"
    ) as f:
        for line in f:
            addr, bal = line.split()
            alloc[bytes.fromhex(addr)] = int(bal)
    return GenesisSpec(
        alloc=alloc,
        difficulty=0x400000000,
        gas_limit=0x1388,
        timestamp=0,
        extra_data=bytes.fromhex(
            "11bbe8db4e347b4e8c937c1c8370e4b5ed33adb3db69cbdb7a38e1e50b1b82fa"
        ),
        nonce=bytes.fromhex("0000000000000042"),
    )


@pytest.fixture(scope="session")
def epoch0_cache():
    """Full spec-size epoch-0 cache (~16 MiB, ~10 s to generate);
    persisted outside the tree so repeat runs skip the generation."""
    path = "/tmp/khipu_ethash_epoch0_cache.npy"
    n_rows = cache_size(0) // 64
    if os.path.exists(path):
        rows = np.load(path)
        if rows.shape == (n_rows, 16):
            cache = EthashCache.__new__(EthashCache)
            cache.epoch = 0
            cache.seed = seed_hash(0)
            cache.cache = rows
            cache.n_rows = n_rows
            return cache
    cache = EthashCache(0)
    np.save(path, cache.cache)
    return cache


class TestMainnetBlock1:
    def test_header_identity(self):
        """Every header byte is load-bearing for this keccak identity."""
        assert BLOCK1.hash == bytes.fromhex(
            "88e96d4537bea4d9c05d12549907b32561d3bf31f45aae734cdc119f13406cb6"
        )
        assert BlockHeader.decode(BLOCK1.encode()) == BLOCK1

    def test_block1_pow_validates(self, epoch0_cache):
        """Full-size Ethash validation of a real mainnet seal — the
        one check that cannot pass on invented data."""
        pow_hash = keccak256(BLOCK1.encode_without_nonce())
        assert check_pow(
            epoch0_cache,
            pow_hash,
            BLOCK1.mix_hash,
            int.from_bytes(BLOCK1.nonce, "big"),
            BLOCK1.difficulty,
        )
        # and it is nonce-sensitive: any other seal fails
        assert not check_pow(
            epoch0_cache,
            pow_hash,
            BLOCK1.mix_hash,
            int.from_bytes(BLOCK1.nonce, "big") ^ 1,
            BLOCK1.difficulty,
        )

    def test_difficulty_calculator_matches_mainnet(self):
        """Frontier difficulty rule reproduces block 1's on-chain
        difficulty from the genesis header."""
        cfg = KhipuConfig()  # mainnet fork schedule
        genesis = BlockHeader(
            parent_hash=b"\x00" * 32,
            ommers_hash=BLOCK1.ommers_hash,
            beneficiary=b"\x00" * 20,
            state_root=GENESIS_STATE_ROOT,
            transactions_root=EMPTY_TRIE_HASH,
            receipts_root=EMPTY_TRIE_HASH,
            logs_bloom=b"\x00" * 256,
            difficulty=0x400000000,
            number=0,
            gas_limit=0x1388,
            gas_used=0,
            unix_timestamp=0,
            extra_data=b"",
            mix_hash=b"\x00" * 32,
            nonce=b"\x00" * 8,
        )
        assert (
            calc_difficulty(
                BLOCK1.unix_timestamp, genesis, cfg.blockchain
            )
            == BLOCK1.difficulty
        )

    def test_replay_genesis_to_block1(self, epoch0_cache):
        """End-to-end replay of real mainnet block 1 through the full
        driver: header validation (difficulty + PoW seal) then
        execution; the persisted state root must hit the
        PoW-authenticated header root. Exercises the mainnet genesis
        alloc (8893 accounts), the MPT, account RLP, and the Frontier
        5-ETH block reward against an oracle this repo did not
        produce."""
        cfg = KhipuConfig()  # mainnet schedule + monetary policy
        bc = Blockchain(Storages(), cfg)
        genesis = bc.load_genesis(mainnet_genesis_spec())
        assert genesis.hash == GENESIS_HASH  # sanity: right pre-state

        driver = ReplayDriver(bc, cfg)
        driver.header_validator.seal_check = lambda h: check_pow(
            epoch0_cache,
            keccak256(h.encode_without_nonce()),
            h.mix_hash,
            int.from_bytes(h.nonce, "big"),
            h.difficulty,
        )
        stats = driver.replay([Block(BLOCK1, BlockBody())])
        assert stats.blocks == 1
        assert bc.best_block_number == 1
        # save_block verified persisted-root == header.state_root; make
        # the anchor explicit anyway:
        assert (
            bc.get_header_by_number(1).state_root == BLOCK1.state_root
        )
        # the miner holds exactly the 5 ETH Frontier reward
        miner = bc.get_account(BLOCK1.beneficiary, BLOCK1.state_root)
        assert miner.balance == 5 * 10**18
