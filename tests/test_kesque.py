"""Kesque log-structured engine (khipu_tpu/storage/segment.py,
storage/kesque.py, sync/fast_sync.py segment ingest, cluster
segment-ship — docs/kesque.md).

The headline guarantees under test: the frame codec round-trips and a
torn tail is truncated at EVERY byte boundary of the final frame; the
sidecar index checkpoint and the rebuild-on-open path agree bit-exact;
``Storages(engine="kesque")`` replays the transfer AND contract
fixtures to the identical chain the sqlite engine produces; 120 seeded
kills across the ``kesque.append`` / ``kesque.roll`` /
``kesque.index`` seams always recover bit-exact after a restart-style
reopen; compaction under concurrent readers never serves a wrong byte;
and a mixed-backend rebalance join negotiates down to the paged
transport and lands at exactly the old or the new epoch — never
between."""

import dataclasses
import os
import threading

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.chaos import FaultPlan, FaultRule, InjectedDeath, active
from khipu_tpu.config import SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.storage.compactor import verify_reachable
from khipu_tpu.storage.kesque import (
    KesqueEngine,
    KesqueStore,
    TAG_NODE,
    decode_record,
    encode_del_record,
    encode_node_record,
    encode_put_record,
)
from khipu_tpu.storage.segment import (
    FRAME_HEADER,
    Segment,
    frame,
    scan_frames,
)
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.fast_sync import segment_snapshot_ingest
from khipu_tpu.sync.journal import recover
from khipu_tpu.sync.replay import CollectorDied, ReplayDriver

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(4)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18
MINER = b"\xaa" * 20
ALLOC = {a: 1000 * ETH for a in ADDRS}
N_BLOCKS = 12

# contract with storage slots AND deployed runtime code, so fixtures
# cross all three node stores (same shape as test_fast_sync)
_RUNTIME = bytes.fromhex("60005460005260206000f3")
_SSTORES = bytes.fromhex("602a600055600b600155")
_COPY = bytes(
    [0x60, len(_RUNTIME), 0x60, len(_SSTORES) + 12, 0x60, 0x00, 0x39,
     0x60, len(_RUNTIME), 0x60, 0x00, 0xF3]
)
INIT = _SSTORES + _COPY + _RUNTIME


def _tx(i, nonce, to, value, payload=b"", gas=21_000):
    return sign_transaction(
        Transaction(nonce, 10**9, gas, to, value, payload),
        KEYS[i], chain_id=1,
    )


@pytest.fixture(scope="module")
def transfer_chain():
    """The 12-block transfer fixture (test_chaos shape): enough
    windows for a depth-2 pipeline to be mid-flight when a fault
    lands."""
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG, GenesisSpec(alloc=ALLOC)
    )
    blocks = []
    nonces = [0, 0, 0, 0]
    for n in range(N_BLOCKS):
        i = n % len(KEYS)
        blocks.append(
            builder.add_block(
                [_tx(i, nonces[i], ADDRS[(i + 1) % 4], 100 + n)],
                coinbase=MINER,
            )
        )
        nonces[i] += 1
    return blocks


@pytest.fixture(scope="module")
def contract_chain():
    """The contract fixture: a deploy (state + storage + code) plus a
    transfer, so replay parity covers all three node topics."""
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG, GenesisSpec(alloc=ALLOC)
    )
    return [
        builder.add_block(
            [_tx(0, 0, None, 0, INIT, gas=200_000)], coinbase=MINER
        ),
        builder.add_block(
            [_tx(0, 1, ADDRS[1], 5 * ETH)], coinbase=MINER
        ),
    ]


def _cfg(window=2, depth=2, degrade=False):
    return dataclasses.replace(
        CFG,
        sync=SyncConfig(
            parallel_tx=False,
            commit_window_blocks=window,
            pipeline_depth=depth,
            degrade_on_collector_death=degrade,
            collector_join_timeout=5.0,
            adaptive_commit=False,
        ),
    )


def _replay_into(storages, chain, cfg=None):
    cfg = cfg or _cfg()
    bc = Blockchain(storages, cfg)
    bc.load_genesis(GenesisSpec(alloc=ALLOC))
    ReplayDriver(bc, cfg).replay(chain)
    return bc


def _assert_same_chain(bc, ref, upto):
    assert bc.best_block_number == ref.best_block_number == upto
    for n in range(upto + 1):
        a, b = bc.get_header_by_number(n), ref.get_header_by_number(n)
        assert a is not None and a.hash == b.hash, f"block {n} diverged"


# ------------------------------------------------------ frame codec


class TestFrameCodec:
    def test_frame_roundtrip_various_sizes(self, tmp_path):
        payloads = [b"", b"x", b"y" * 7, b"z" * 100, b"w" * 5000]
        blob = b"".join(frame(p) for p in payloads)
        frames, end = scan_frames(blob)
        assert [p for _off, p in frames] == payloads
        assert end == len(blob)
        # offsets address the frames exactly
        for off, p in frames:
            one, _ = scan_frames(blob[off : off + FRAME_HEADER + len(p)])
            assert one == [(0, p)]

    def test_record_codec_roundtrip(self):
        assert decode_record(encode_node_record(b"rlp")) == (
            TAG_NODE, None, b"rlp"
        )
        tag, key, value = decode_record(encode_put_record(b"k", b"v"))
        assert (key, value) == (b"k", b"v") and tag != TAG_NODE
        tag, key, value = decode_record(encode_del_record(b"gone"))
        assert key == b"gone" and value == b""

    def test_scan_stops_at_bitflip(self):
        payloads = [b"a" * 40, b"b" * 40, b"c" * 40]
        blob = bytearray(b"".join(frame(p) for p in payloads))
        blob[FRAME_HEADER + 45 + 5] ^= 0xFF  # inside frame 2's payload
        frames, end = scan_frames(bytes(blob))
        assert [p for _o, p in frames] == [b"a" * 40]
        assert end == FRAME_HEADER + 40

    def test_append_many_matches_per_record_append(self, tmp_path):
        payloads = [b"p%d" % i * (i + 1) for i in range(20)]
        one = Segment(str(tmp_path / "one.kseg"), 0)
        locs_one = [one.append(p) for p in payloads]
        many = Segment(str(tmp_path / "many.kseg"), 0)
        locs_many = many.append_many(payloads)
        assert locs_one == locs_many
        assert one.end == many.end
        for (off, _rec), p in zip(locs_many, payloads):
            assert many.read(off) == p
        one.close(), many.close()

    def test_read_chunk_cuts_on_frame_boundaries(self, tmp_path):
        seg = Segment(str(tmp_path / "s.kseg"), 0)
        payloads = [b"r%03d" % i * 20 for i in range(50)]
        seg.append_many(payloads)
        got, offset, done = [], 0, False
        while not done:
            raw, offset, done = seg.read_chunk(offset, 300)
            frames, end = scan_frames(raw)
            assert end == len(raw)  # whole frames only
            got.extend(p for _o, p in frames)
        assert got == payloads
        # a single frame larger than max_bytes still ships whole
        raw, nxt, done = seg.read_chunk(0, 1)
        assert scan_frames(raw)[0][0][1] == payloads[0]
        seg.close()


# -------------------------------------------------------- torn tails


class TestTornTail:
    def test_truncation_at_every_byte_boundary_of_final_frame(
            self, tmp_path):
        """THE crash-contract sweep: cut the file after every single
        byte of the final frame (header bytes included) — open must
        keep exactly the complete leading frames and truncate the
        rest, every time."""
        payloads = [b"first" * 10, b"second" * 10, b"final" * 10]
        seed = Segment(str(tmp_path / "seed.kseg"), 0)
        locs = seed.append_many(payloads)
        seed.close()
        with open(str(tmp_path / "seed.kseg"), "rb") as f:
            full = f.read()
        last_off = locs[-1][0]
        for cut in range(last_off, len(full)):
            p = str(tmp_path / f"cut{cut}.kseg")
            with open(p, "wb") as f:
                f.write(full[:cut])
            seg, torn = Segment.open(p, 0)
            assert torn == cut - last_off
            assert seg.end == last_off
            assert [pl for _o, pl in seg.scan()] == payloads[:2]
            seg.unlink()
        # and the untouched file loses nothing
        seg, torn = Segment.open(str(tmp_path / "seed.kseg"), 0)
        assert torn == 0 and [p for _o, p in seg.scan()] == payloads
        seg.close()

    def test_store_reopen_truncates_torn_tail(self, tmp_path):
        st = KesqueStore(str(tmp_path), "account", content_addressed=True)
        data = {keccak256(b"v%d" % i): b"v%d" % i for i in range(30)}
        st.append_batch([], data)
        st.stop()
        # a power cut mid-append: garbage past the committed end
        seg_dir = os.path.join(str(tmp_path), "kesque", "account")
        name = sorted(os.listdir(seg_dir))[-2]  # newest .kseg (not .kidx)
        assert name.endswith(".kseg")
        with open(os.path.join(seg_dir, name), "ab") as f:
            f.write(b"\xde\xad\xbe\xef torn tail bytes")
        st2 = KesqueStore(str(tmp_path), "account", content_addressed=True)
        assert st2.torn_bytes > 0
        assert not st2.rebuilt_index  # sidecar still valid post-repair
        for k, v in data.items():
            assert st2.get(k) == v
        st2.stop()

    def test_recovery_report_surfaces_storage_repairs(self, tmp_path):
        cfg = _cfg(window=1, depth=1)
        st = Storages(engine="kesque", data_dir=str(tmp_path))
        bc = Blockchain(st, cfg)
        bc.load_genesis(GenesisSpec(alloc=ALLOC))
        st.stop()
        seg_dir = os.path.join(str(tmp_path), "kesque", "account")
        seg = [n for n in sorted(os.listdir(seg_dir))
               if n.endswith(".kseg")][-1]
        with open(os.path.join(seg_dir, seg), "ab") as f:
            f.write(b"torn")
        st2 = Storages(engine="kesque", data_dir=str(tmp_path))
        bc2 = Blockchain(st2, cfg)
        report = recover(bc2, config=cfg)
        assert any(
            line.startswith("storage:") and "torn segment tail" in line
            for line in report.actions
        ), report.actions
        st2.stop()


# ------------------------------------------------- index lifecycle


class TestIndexLifecycle:
    def _data(self, n, tag=0):
        return {
            keccak256(b"node-%d-%d" % (tag, i)): b"node-%d-%d" % (tag, i)
            for i in range(n)
        }

    def test_sidecar_checkpoint_fast_open(self, tmp_path):
        data = self._data(50)
        st = KesqueStore(str(tmp_path), "account", content_addressed=True)
        st.append_batch([], data)
        st.stop()  # checkpoints the sidecar
        st2 = KesqueStore(str(tmp_path), "account", content_addressed=True)
        assert not st2.rebuilt_index
        assert st2.count == len(data)
        for k, v in data.items():
            assert st2.get(k) == v
        st2.stop()

    def test_rebuild_on_missing_sidecar_is_bit_exact(self, tmp_path):
        data = self._data(50)
        st = KesqueStore(str(tmp_path), "account", content_addressed=True)
        st.append_batch([], data)
        st.stop()
        sidecar = [
            n for n in os.listdir(
                os.path.join(str(tmp_path), "kesque", "account"))
            if n.endswith(".kidx")
        ]
        assert sidecar
        os.unlink(os.path.join(
            str(tmp_path), "kesque", "account", sidecar[0]))
        st2 = KesqueStore(str(tmp_path), "account", content_addressed=True)
        assert st2.rebuilt_index  # full scan, no sidecar
        assert st2.count == len(data)
        assert sorted(st2.keys()) == sorted(data)
        for k, v in data.items():
            assert st2.get(k) == v
        st2.stop()

    def test_stale_sidecar_tail_scan_applies_missing_records(
            self, tmp_path):
        """Records appended after the last checkpoint but before a
        crash are recovered by the tail scan past the sidecar
        watermarks — no full rebuild, nothing lost."""
        early, late = self._data(30, tag=1), self._data(30, tag=2)
        st = KesqueStore(str(tmp_path), "account", content_addressed=True)
        st.append_batch([], early)
        st.checkpoint()
        st.append_batch([], late)  # never checkpointed
        for seg in st._segments.values():
            seg.close()  # crash: fds drop, sidecar stays stale
        st2 = KesqueStore(str(tmp_path), "account", content_addressed=True)
        assert not st2.rebuilt_index
        for k, v in {**early, **late}.items():
            assert st2.get(k) == v
        st2.stop()

    def test_tombstone_and_overwrite_survive_reopen(self, tmp_path):
        st = KesqueStore(str(tmp_path), "kv", content_addressed=False)
        st.append_batch([], {b"a": b"1", b"b": b"2"})
        st.append_batch([b"b"], {b"a": b"3"})  # delete b, overwrite a
        st.stop()
        st2 = KesqueStore(str(tmp_path), "kv", content_addressed=False)
        assert st2.get(b"a") == b"3"
        assert st2.get(b"b") is None
        assert st2.count == 1
        st2.stop()


# ------------------------------------------------- segment ingest


class TestSegmentIngest:
    def _engine_with(self, tmp_path, name, data):
        eng = KesqueEngine(str(tmp_path / name))
        eng.store("account").append_batch([], data)
        return eng

    def test_ingest_chunk_raw_splice_roundtrip(self, tmp_path):
        data = {keccak256(b"n%d" % i): b"n%d" % i for i in range(200)}
        src = self._engine_with(tmp_path, "src", data)
        dst = KesqueEngine(str(tmp_path / "dst"))
        total = 0
        for topic, seq, _size in src.list_segments(["account"]):
            off, done = 0, False
            while not done:
                raw, off, done = src.read_chunk(topic, seq, off, 4096)
                n, corrupt = dst.ingest_chunk(topic, raw)
                assert corrupt == 0
                total += n
        assert total == len(data)
        for k, v in data.items():
            assert dst.store("account").get(k) == v
        dst.stop()
        # the spliced log is a VALID log: a from-scratch index rebuild
        # (no sidecar) reproduces every record bit-exact
        sc_dir = os.path.join(str(tmp_path / "dst"), "kesque", "account")
        for n in os.listdir(sc_dir):
            if n.endswith(".kidx"):
                os.unlink(os.path.join(sc_dir, n))
        re = KesqueEngine(str(tmp_path / "dst"))
        assert re.store("account").rebuilt_index
        for k, v in data.items():
            assert re.store("account").get(k) == v
        re.stop(), src.stop()

    def test_ingest_chunk_rejects_foreign_and_torn_frames(self, tmp_path):
        dst = KesqueEngine(str(tmp_path / "d"))
        node = encode_node_record(b"good node rlp")
        put = encode_put_record(b"k", b"not a node")
        n, corrupt = dst.ingest_chunk("account", frame(node) + frame(put))
        assert (n, corrupt) == (1, 1)  # node admitted, put rejected
        torn = frame(node) + frame(encode_node_record(b"lost"))[:7]
        n, corrupt = dst.ingest_chunk("account", torn)
        assert n == 1  # the complete frame still lands
        # a bit-flipped chunk admits NOTHING under a wrong key
        flipped = bytearray(frame(encode_node_record(b"payload")))
        flipped[FRAME_HEADER + 3] ^= 0xFF
        n, _ = dst.ingest_chunk("account", bytes(flipped))
        assert n == 0
        for k in dst.store("account").keys():
            v = dst.store("account").get(k)
            assert keccak256(v) == k  # every admitted key content-checks
        dst.stop()

    def test_segment_snapshot_ingest_end_to_end(self, contract_chain,
                                                tmp_path):
        """Parallel segment streaming of a real multi-store trie, with
        the target-root reachability walk — the fast-sync bulk path."""
        src_bc = _replay_into(Storages(), contract_chain)
        root = src_bc.get_header_by_number(2).state_root
        src = KesqueEngine(str(tmp_path / "src"))
        for topic, store in (
            ("account", src_bc.storages.account_node_storage),
            ("storage", src_bc.storages.storage_node_storage),
            ("evmcode", src_bc.storages.evmcode_storage),
        ):
            src.store(topic).append_batch([], {
                bytes(k): store.get(k) for k in store.source.keys()
            })
        dst = Storages(engine="kesque", data_dir=str(tmp_path / "dst"))
        report = segment_snapshot_ingest(
            dst, lambda: src.list_segments(), src.read_chunk,
            target_root=root, workers=3,
        )
        assert report.missing == 0 and report.corrupt_nodes == 0
        assert report.records > 0 and report.corrupt_frames == 0
        assert dst.app_state.fast_sync_done
        walk = verify_reachable(
            dst.account_node_storage, dst.storage_node_storage,
            dst.evmcode_storage, root, verify_hashes=True,
        )
        assert walk.missing == 0 and walk.corrupt == 0
        assert walk.storage_nodes > 0 and walk.code_blobs > 0
        tgt_bc = Blockchain(dst, CFG)
        assert tgt_bc.get_account(ADDRS[1], root).balance == 1005 * ETH
        dst.stop(), src.stop()


# ----------------------------------------------- replay parity


class TestReplayParity:
    @pytest.mark.parametrize("fixture", ["transfer", "contract"])
    def test_kesque_replays_fixture_bit_exact_vs_sqlite(
            self, fixture, transfer_chain, contract_chain, tmp_path):
        chain = transfer_chain if fixture == "transfer" else contract_chain
        kq = Storages(engine="kesque", data_dir=str(tmp_path / "kq"))
        sq = Storages(engine="sqlite", data_dir=str(tmp_path / "sq"))
        bc_kq = _replay_into(kq, chain)
        bc_sq = _replay_into(sq, chain)
        _assert_same_chain(bc_kq, bc_sq, len(chain))
        for n in range(len(chain) + 1):
            a = bc_kq.get_header_by_number(n)
            b = bc_sq.get_header_by_number(n)
            assert a.state_root == b.state_root, f"root {n} diverged"
        # durability: a restart-style reopen serves the same chain
        kq.stop(), sq.stop()
        kq2 = Storages(engine="kesque", data_dir=str(tmp_path / "kq"))
        bc2 = Blockchain(kq2, _cfg())
        _assert_same_chain(bc2, bc_sq, len(chain))
        walk = verify_reachable(
            kq2.account_node_storage, kq2.storage_node_storage,
            kq2.evmcode_storage,
            bc2.get_header_by_number(len(chain)).state_root,
            verify_hashes=True,
        )
        assert walk.missing == 0 and walk.corrupt == 0
        kq2.stop()


# --------------------------------- compaction under concurrent reads


class TestCompaction:
    def test_compaction_under_concurrent_reads_bit_exact(
            self, transfer_chain, tmp_path):
        st = Storages(engine="kesque", data_dir=str(tmp_path))
        bc = _replay_into(st, transfer_chain)
        root = bc.get_header_by_number(N_BLOCKS).state_root
        store = st.kesque_engine.store("account")
        oracle = {k: store.get(k) for k in store.keys()}
        assert oracle
        stop_flag = threading.Event()
        errors = []

        def reader():
            keys = sorted(oracle)
            i = 0
            while not stop_flag.is_set():
                k = keys[i % len(keys)]
                v = store.get(k)
                # a key may vanish mid-compaction (unreachable record
                # swept); what is NEVER allowed is a wrong byte
                if v is not None and v != oracle[k]:
                    errors.append((k, v))
                i += 1

        readers = [threading.Thread(target=reader) for _ in range(3)]
        for t in readers:
            t.start()
        try:
            report = st.kesque_engine.compact(root)
        finally:
            stop_flag.set()
            for t in readers:
                t.join(timeout=10)
        assert not errors, f"corrupt read during compaction: {errors[:3]}"
        assert report.corrupt == 0
        assert report.reclaimed_bytes >= 0
        assert report.segment_stats["account"]
        # post-compaction: every surviving record bit-exact, the full
        # state still verifies, the chain still serves
        for k in store.keys():
            assert store.get(k) == oracle[k]
        walk = verify_reachable(
            st.account_node_storage, st.storage_node_storage,
            st.evmcode_storage, root, verify_hashes=True,
        )
        assert walk.missing == 0 and walk.corrupt == 0
        assert bc.best_block_number == N_BLOCKS
        st.stop()


# -------------------------------------------- kill-mid-append sweep


def _small_segments(storages, nbytes=1 << 13):
    """Shrink every topic's roll threshold so the sweep actually
    crosses segment boundaries (64 MiB segments would never roll on a
    12-block fixture)."""
    for store in storages.kesque_engine._stores.values():
        store.segment_bytes = max(1 << 12, nbytes)


def _hard_close(storages):
    """Simulated process death: drop the crashed instance's fds
    WITHOUT flushing or checkpointing — a clean ``stop()`` would write
    the very sidecar the crash is supposed to have lost."""
    for store in storages.kesque_engine._stores.values():
        for seg in store._segments.values():
            seg.close()


@pytest.mark.chaos
class TestKillMidAppendSweep:
    def test_kill_mid_append_sweep_120_seeds(self, transfer_chain,
                                             tmp_path):
        """THE acceptance sweep: 120 seeded deaths across the
        ``kesque.append`` (chunked frame writes), ``kesque.roll``
        (segment boundary) and ``kesque.index`` (sidecar checkpoint)
        seams. Whatever the seed kills, a restart-style reopen of the
        same data_dir + journal recovery + a serial resume lands on
        the bit-exact chain."""
        ref_cfg = _cfg(window=1, depth=1)
        ref = Blockchain(Storages(), ref_cfg)
        ref.load_genesis(GenesisSpec(alloc=ALLOC))
        ReplayDriver(ref, ref_cfg).replay(transfer_chain)
        sites = ("kesque.append", "kesque.roll", "kesque.index")
        killed = survived = 0
        for seed in range(120):
            d = str(tmp_path / f"s{seed}")
            cfg = _cfg(window=2, depth=2)
            st = Storages(engine="kesque", data_dir=d)
            _small_segments(st)
            bc = Blockchain(st, cfg)
            bc.load_genesis(GenesisSpec(alloc=ALLOC))
            plan = FaultPlan(
                seed=seed,
                rules=[FaultRule(sites[seed % len(sites)], "die",
                                 times=1,
                                 after=(seed // len(sites)) % 40)],
            )
            with active(plan):
                try:
                    drv = ReplayDriver(bc, cfg)
                    drv.replay(transfer_chain[:6])
                    st.kesque_engine.checkpoint()  # live index seam
                    drv.replay(transfer_chain[6:])
                    st.kesque_engine.checkpoint()
                    survived += 1
                except (CollectorDied, InjectedDeath):
                    killed += 1
            # restart semantics: the crashed instance's memory dies
            # with it — reopen the SAME data_dir from disk
            _hard_close(st)
            st2 = Storages(engine="kesque", data_dir=d)
            _small_segments(st2)
            bc2 = Blockchain(st2, cfg)
            if bc2.get_header_by_number(0) is None:
                bc2.load_genesis(GenesisSpec(alloc=ALLOC))
            recover(bc2, config=cfg)
            assert st2.window_journal.pending() == []
            if bc2.best_block_number < N_BLOCKS:
                resume_cfg = _cfg(window=1, depth=1)
                ReplayDriver(bc2, resume_cfg).replay(
                    transfer_chain[bc2.best_block_number:]
                )
            _assert_same_chain(bc2, ref, N_BLOCKS)
            _hard_close(st2)
        # the harness genuinely exercised both outcomes
        assert killed > 20 and survived > 20, (killed, survived)


# ------------------------------------- mixed-backend rebalance join


class FakeShard:
    """In-memory BridgeClient stand-in (test_rebalance shape) — the
    paged rebalance surface only; ``engine_info`` is answered by the
    sqlite-flavoured and kesque-flavoured subclasses."""

    def __init__(self):
        self.store = {}
        self.fail = False

    def get_node_data(self, hashes):
        return {h: self.store[h] for h in hashes if h in self.store}

    def put_node_data(self, nodes):
        self.store.update(nodes)
        return len(nodes)

    def stream_node_data(self, ranges, cursor, count):
        from khipu_tpu.cluster.ring import _point

        snap = dict(self.store)
        keys = sorted(
            k for k in snap
            if cursor < k and any(lo <= _point(k) < hi
                                  for lo, hi in ranges)
        )
        page = keys[:count]
        done = len(keys) <= count
        nxt = page[-1] if page else bytes(cursor)
        return done, nxt, [(k, snap[k]) for k in page]

    def ping(self, payload=b""):
        return payload

    def close(self):
        pass


class SqliteShard(FakeShard):
    def engine_info(self):
        return "sqlite", []


class KesqueShard(FakeShard):
    """Kesque-capable shard: paged surface plus the segment-ship
    surface, served from a shared source engine."""

    def __init__(self, engine):
        super().__init__()
        self.engine = engine
        self.chunk_calls = 0
        self.fail_chunk_after = None  # test hook: die mid-ship
        self.corrupt_chunks = False

    def engine_info(self):
        return "kesque", self.engine.list_segments(["account"])

    def stream_segments(self, topic, seq, offset, max_bytes):
        self.chunk_calls += 1
        if (self.fail_chunk_after is not None
                and self.chunk_calls > self.fail_chunk_after):
            raise ConnectionError("segment source died mid-ship")
        raw, nxt, done = self.engine.read_chunk(
            topic, seq, offset, max_bytes
        )
        if self.corrupt_chunks and raw:
            raw = b"\x00" + raw[1:]
        return raw, nxt, done


def _mixed_cluster(tmp_path, shard_kinds, data, extra_kinds=()):
    """Cluster where each member is kesque- or sqlite-backed.
    ``shard_kinds``/``extra_kinds``: {endpoint: "kesque"|"sqlite"}."""
    from khipu_tpu.cluster import Rebalancer, ShardedNodeClient

    engine = KesqueEngine(str(tmp_path / "ship_src"))
    engine.store("account").append_batch([], data)
    shards = {}
    for ep, kind in {**shard_kinds, **dict(extra_kinds)}.items():
        shards[ep] = (KesqueShard(engine) if kind == "kesque"
                      else SqliteShard())
    cl = ShardedNodeClient(
        list(shard_kinds),
        channel_factory=lambda ep: shards[ep],
        replication=2, vnodes=8, max_retries=1, sleep=lambda s: None,
    )
    rb = Rebalancer(cl, batch=64)
    cl.replicate(data)
    return cl, rb, shards, engine


def _dataset(n):
    vals = [b"mpt node rlp bytes #%d" % i for i in range(n)]
    return {keccak256(v): v for v in vals}


class TestMixedBackendRebalance:
    def test_mixed_backends_negotiate_down_and_land_new_epoch(
            self, tmp_path):
        """One sqlite member in the ring: negotiation must fall back
        to the paged transport (zero segment chunks) and the join
        still lands at EXACTLY the new epoch, bit-exact."""
        data = _dataset(300)
        cl, rb, shards, eng = _mixed_cluster(
            tmp_path,
            {"a": "kesque", "b": "kesque", "c": "sqlite"},
            data, extra_kinds={"d": "kesque"},
        )
        e0 = cl.ring.epoch
        streamed = rb.join("d")
        assert streamed > 0
        assert cl.ring.epoch == e0 + 1  # exactly the new epoch
        assert not cl.ring.in_transition
        assert rb.segment_chunks == 0  # negotiated down
        assert cl.fetch(list(data)) == data
        eng.stop()

    def test_all_kesque_join_uses_segment_ship(self, tmp_path):
        data = _dataset(300)
        cl, rb, shards, eng = _mixed_cluster(
            tmp_path,
            {"a": "kesque", "b": "kesque", "c": "kesque"},
            data, extra_kinds={"d": "kesque"},
        )
        e0 = cl.ring.epoch
        streamed = rb.join("d")
        assert streamed > 0
        assert rb.segment_chunks > 0  # the bulk transport ran
        assert cl.ring.epoch == e0 + 1
        assert not cl.ring.in_transition
        assert cl.fetch(list(data)) == data
        # every key the new epoch assigns to d actually landed on d
        for k, v in data.items():
            if "d" in cl.ring.replicas_for(k):
                assert shards["d"].store[k] == v
        eng.stop()

    def test_ship_failure_mid_stream_falls_back_and_lands_exactly(
            self, tmp_path):
        """The source dies mid segment-ship: the join must end at
        exactly the old or the new epoch — here the paged fallback
        completes it at the new one, with full readback."""
        data = _dataset(300)
        cl, rb, shards, eng = _mixed_cluster(
            tmp_path,
            {"a": "kesque", "b": "kesque", "c": "kesque"},
            data, extra_kinds={"d": "kesque"},
        )
        for sh in shards.values():
            if isinstance(sh, KesqueShard):
                sh.fail_chunk_after = 1
        e0 = cl.ring.epoch
        rb.join("d")
        assert cl.ring.epoch in (e0, e0 + 1)
        assert cl.ring.epoch == e0 + 1  # fallback completed the join
        assert not cl.ring.in_transition
        assert cl.fetch(list(data)) == data
        eng.stop()

    def test_corrupt_chunk_detected_and_fallback_lands_exactly(
            self, tmp_path):
        data = _dataset(200)
        cl, rb, shards, eng = _mixed_cluster(
            tmp_path,
            {"a": "kesque", "b": "kesque", "c": "kesque"},
            data, extra_kinds={"d": "kesque"},
        )
        for sh in shards.values():
            if isinstance(sh, KesqueShard):
                sh.corrupt_chunks = True
        e0 = cl.ring.epoch
        rb.join("d")
        assert cl.ring.epoch == e0 + 1 and not cl.ring.in_transition
        assert cl.fetch(list(data)) == data  # nothing corrupt admitted
        eng.stop()

    def test_abort_mid_join_stays_at_old_epoch(self, tmp_path):
        """The other half of exactly-old-or-new: a death on the
        rebalance stream seam aborts the transition — the ring stays
        at the OLD epoch, not in between."""
        data = _dataset(200)
        cl, rb, shards, eng = _mixed_cluster(
            tmp_path,
            {"a": "kesque", "b": "kesque", "c": "sqlite"},
            data, extra_kinds={"d": "kesque"},
        )
        e0 = cl.ring.epoch
        plan = FaultPlan(
            seed=7,
            rules=[FaultRule("rebalance.stream", "die", times=1)],
        )
        with active(plan):
            with pytest.raises(InjectedDeath):
                rb.join("d")
        # mid-join death: the COMMITTED epoch is still the old one and
        # serves bit-exact — never a half-epoch
        assert cl.ring.epoch == e0
        assert set(cl.ring.members) == {"a", "b", "c"}
        assert cl.fetch(list(data)) == data
        # recovery settles the open transition to exactly old or new
        outcome = rb.recover()
        assert outcome in ("resumed", "rolled_back")
        assert cl.ring.epoch in (e0, e0 + 1)
        assert not cl.ring.in_transition
        assert cl.fetch(list(data)) == data
        eng.stop()


# -------------------------------------------------- observability


class TestObservability:
    def test_engine_registry_families_once_each(self, tmp_path):
        eng = KesqueEngine(str(tmp_path))
        eng.store("account").append_batch(
            [], {keccak256(b"x"): b"x"}
        )
        names = [s[0] for s in eng._registry_samples()]
        for fam in (
            "khipu_kesque_segments",
            "khipu_kesque_live_bytes",
            "khipu_kesque_garbage_bytes",
            "khipu_kesque_index_entries",
            "khipu_kesque_appended_bytes_total",
            "khipu_kesque_reclaimed_bytes_total",
            "khipu_kesque_torn_bytes_total",
            "khipu_kesque_compactions_total",
            "khipu_kesque_read_amplification",
        ):
            assert names.count(fam) == 1, fam
        eng.stop()
