"""Tx passport truth under failure (observability/journey.py —
docs/observability.md "Transaction passport").

The headline guarantees: a reorg-retracted tx's journey shows the
retraction page and then its re-inclusion (``via=mined`` on the
adopted branch, or ``via=pool`` residence for orphan-only txs); a
journey for a tx whose window died mid background save truthfully
ends BEFORE the persist-durable page and resumes after ``recover()``;
and a replay with the board disabled allocates NOTHING on the board
while landing on a bit-exact chain vs the instrumented run.
"""

import dataclasses

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.chaos import FaultPlan, FaultRule, active
from khipu_tpu.config import SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.observability.journey import (
    JOURNEY,
    JourneyBoard,
    journey_sampled,
    use_node,
)
from khipu_tpu.observability.registry import MetricsRegistry
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.journal import recover
from khipu_tpu.sync.reorg import ReorgManager
from khipu_tpu.sync.replay import CollectorDied, ReplayDriver, ReplayStats
from khipu_tpu.txpool import PendingTransactionsPool

pytestmark = pytest.mark.chaos

CFG = dataclasses.replace(
    fixture_config(chain_id=1),
    sync=SyncConfig(commit_window_blocks=1, parallel_tx=False),
)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(4)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18
ALLOC = {a: 1000 * ETH for a in ADDRS}
GEN = GenesisSpec(alloc=ALLOC)
MINER_A = b"\xaa" * 20
MINER_B = b"\xbb" * 20


@pytest.fixture(autouse=True)
def _clean_board():
    """Every test starts and leaves with a disabled, empty board —
    journey state must never leak across tests (or into other files
    sharing the process)."""
    JOURNEY.disable()
    JOURNEY.reset()
    yield
    JOURNEY.disable()
    JOURNEY.reset()


def _tx(i, nonce, to, value, gas_price=10**9):
    return sign_transaction(
        Transaction(nonce, gas_price, 21_000, to, value),
        KEYS[i], chain_id=1,
    )


def build(n, diverge_at=None, value_off=0):
    """Consensus-true chain of ``n`` transfer blocks; from
    ``diverge_at`` on the coinbase flips to MINER_B and values shift
    by ``value_off`` (0 keeps the SAME txs on a different branch — the
    re-mined re-inclusion case)."""
    builder = ChainBuilder(Blockchain(Storages(), CFG), CFG, GEN)
    blocks, nonces = [], [0, 0, 0, 0]
    for k in range(n):
        i = k % 4
        diverged = diverge_at is not None and k >= diverge_at
        blocks.append(builder.add_block(
            [_tx(i, nonces[i], ADDRS[(i + 1) % 4],
                 100 + k + (value_off if diverged else 0))],
            coinbase=MINER_B if diverged else MINER_A,
            timestamp=10 * (k + 1),
        ))
        nonces[i] += 1
    return blocks


@pytest.fixture(scope="module")
def chains():
    return {
        "base": build(8),
        # different txs past the fork point: orphan-only → via=pool
        "fork": build(10, diverge_at=5, value_off=1000),
        # SAME txs past the fork point: re-mined → via=mined
        "mined": build(10, diverge_at=5, value_off=0),
        "long": build(12),
    }


def fresh_node(blocks, upto, config=CFG):
    bc = Blockchain(Storages(), config)
    bc.load_genesis(GEN)
    driver = ReplayDriver(bc, config)
    stats = ReplayStats()
    for b in blocks[:upto]:
        driver._execute_and_insert(b, stats)
    return bc, driver


def _edges(j):
    return [e[1] for e in j.events]


def _assert_monotonic(j):
    ts = [e[0] for e in j.events]
    assert ts == sorted(ts), "journey events out of time order"


# ------------------------------------------------------ reorg journeys


class TestReorgJourney:
    def test_retracted_tx_shows_retract_then_pool_residence(self, chains):
        """Orphan-only txs: the journey closes the retract arc with
        ``reorg.reinclude via=pool`` — pool residence IS the
        re-inclusion state while the tx awaits re-mining."""
        JOURNEY.enable()
        bc, driver = fresh_node(chains["base"], 8)
        pool = PendingTransactionsPool()
        mgr = ReorgManager(bc, CFG, driver=driver, txpool=pool)
        mgr.switch(5, chains["fork"][5:])
        assert bc.best_block_number == 10

        orphans = [
            stx for b in chains["base"][5:]
            for stx in b.body.transactions
        ]
        assert len(orphans) == 3
        for stx in orphans:
            j = JOURNEY.get(stx.hash)
            assert j is not None, "retracted tx lost from the board"
            edges = _edges(j)
            # the full arc, in order: imported and durable on the
            # losing branch, retracted by the switch, back in the pool
            assert edges.index("ingress") < edges.index("durable")
            assert (edges.index("durable")
                    < edges.index("reorg.retract")
                    < edges.index("reorg.reinclude"))
            _assert_monotonic(j)
            # retraction pins the journey into tail retention
            assert j.pin_reason is not None
            via = [d for (_, e, _, _, d) in j.events
                   if e == "reorg.reinclude"][0]
            assert via["via"] == "pool"
            assert pool.get(stx.hash) is not None

    def test_retracted_tx_remined_on_adopted_branch(self, chains):
        """Same txs on the winning branch: the arc closes with
        ``reorg.reinclude via=mined`` and a second durable page from
        the adopted block's import."""
        JOURNEY.enable()
        bc, driver = fresh_node(chains["base"], 8)
        mgr = ReorgManager(bc, CFG, driver=driver)
        mgr.switch(5, chains["mined"][5:])
        assert bc.best_block_number == 10

        for b in chains["base"][5:]:
            for stx in b.body.transactions:
                j = JOURNEY.get(stx.hash)
                assert j is not None
                edges = _edges(j)
                ri = edges.index("reorg.reinclude")
                assert edges.index("reorg.retract") < ri
                via = j.events[ri][4]
                assert via["via"] == "mined"
                # re-imported on the adopted branch → a second durable
                # page lands after the retraction (the re-import runs
                # during adoption, before finalize stamps re-inclusion)
                last_durable = (len(edges) - 1
                                - edges[::-1].index("durable"))
                assert edges.index("reorg.retract") < last_durable
                assert edges.count("durable") == 2
                _assert_monotonic(j)

    def test_export_shape_for_retracted_journey(self, chains):
        JOURNEY.enable()
        bc, driver = fresh_node(chains["base"], 8)
        mgr = ReorgManager(bc, CFG, driver=driver,
                           txpool=PendingTransactionsPool())
        mgr.switch(5, chains["fork"][5:])
        stx = chains["base"][5].body.transactions[0]
        rec = JOURNEY.export(stx.hash)
        assert rec is not None
        assert rec["txHash"] == "0x" + stx.hash.hex()
        assert rec["pinned"] is not None
        edges = [e["edge"] for e in rec["events"]]
        assert "reorg.retract" in edges and "reorg.reinclude" in edges
        ts = [e["t"] for e in rec["events"]]
        assert ts == sorted(ts)
        for e in rec["events"]:
            assert e["wall"] == pytest.approx(JOURNEY.to_wall(e["t"]))


# -------------------------------------------------- kill mid window


class TestKillMidWindowJourney:
    def _cfg(self, window=2, depth=2):
        return dataclasses.replace(
            CFG,
            sync=SyncConfig(
                parallel_tx=False,
                commit_window_blocks=window,
                pipeline_depth=depth,
                degrade_on_collector_death=False,
                collector_join_timeout=5.0,
                adaptive_commit=False,
            ),
        )

    def test_journey_truthfully_ends_before_durable(self, chains):
        """The collector dies right after block 5's save — block 6 and
        the window's commit mark never land. The passports for BOTH
        window txs must end before the durable page (a saved-but-
        unmarked block is NOT durable), gain a rollback page from
        recovery, and pick the durable page back up on resume."""
        chain = chains["long"]
        cfg = self._cfg()
        JOURNEY.enable()
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GEN)
        plan = FaultPlan(
            seed=3, rules=[FaultRule("collector.save", "die", after=4,
                                     times=1)]
        )
        with active(plan):
            with pytest.raises(CollectorDied):
                ReplayDriver(bc, cfg).replay(chain)
        assert [s for (s, _, _, _) in plan.fired] == ["collector.save"]
        assert bc.storages.app_state.best_block_number == 5

        tx5 = chain[4].body.transactions[0]
        tx6 = chain[5].body.transactions[0]
        for stx in (tx5, tx6):
            j = JOURNEY.get(stx.hash)
            assert j is not None
            edges = _edges(j)
            # the window got as far as its WAL intent...
            assert "ingress" in edges and "seal" in edges
            assert "journal.intent" in edges
            # ...and the passport does NOT claim durability the crash
            # would disprove
            assert "durable" not in edges

        report = recover(bc, config=cfg)
        assert report.rolled_back >= 1
        assert bc.best_block_number == 4
        j5 = JOURNEY.get(tx5.hash)
        edges5 = _edges(j5)
        assert "journal.rollback" in edges5
        assert "durable" not in edges5
        assert j5.pin_reason == "rolled-back"

        # resume where recovery left off: the journey picks the
        # durable page up AFTER the rollback page, still in time order
        resume_cfg = self._cfg(window=1, depth=1)
        ReplayDriver(bc, resume_cfg).replay(chain[4:])
        assert bc.best_block_number == 12
        for stx in (tx5, tx6):
            j = JOURNEY.get(stx.hash)
            edges = _edges(j)
            assert "durable" in edges
            _assert_monotonic(j)
        edges5 = _edges(JOURNEY.get(tx5.hash))
        assert (edges5.index("journal.rollback")
                < len(edges5) - 1 - edges5[::-1].index("durable"))


# ------------------------------------------------- disabled = zero cost


class TestDisabledZeroCost:
    def test_disabled_replay_bit_exact_with_zero_allocations(self, chains):
        """Replay with the board off allocates NOTHING on it (no
        journeys, no event counters) and the chain it lands on is
        bit-exact vs the instrumented run — stamps never steer
        execution."""
        chain = chains["long"]
        cfg = dataclasses.replace(
            CFG,
            sync=SyncConfig(parallel_tx=False, commit_window_blocks=2,
                            pipeline_depth=2, adaptive_commit=False),
        )
        assert not JOURNEY.enabled
        bc_off = Blockchain(Storages(), cfg)
        bc_off.load_genesis(GEN)
        ReplayDriver(bc_off, cfg).replay(chain)
        assert len(JOURNEY) == 0
        assert JOURNEY.events_total == 0
        assert JOURNEY.evicted_total == 0

        JOURNEY.enable()
        bc_on = Blockchain(Storages(), cfg)
        bc_on.load_genesis(GEN)
        ReplayDriver(bc_on, cfg).replay(chain)
        assert len(JOURNEY) > 0
        assert JOURNEY.events_total > 0

        assert (bc_off.best_block_number == bc_on.best_block_number
                == 12)
        for n in range(13):
            a = bc_off.get_header_by_number(n)
            b = bc_on.get_header_by_number(n)
            assert a.hash == b.hash, f"block {n} diverged"
            assert a.state_root == b.state_root


# ----------------------------------------------------- board mechanics


class TestBoardMechanics:
    def _board(self, **kw):
        b = JourneyBoard(**kw)
        b.enable()
        return b

    def test_first_ingress_wins(self):
        b = self._board()
        h = b"\x01" * 32
        b.record(h, "ingress", source="rpc")
        b.record(h, "ingress", source="import")
        j = b.get(h)
        assert len([e for e in j.events if e[1] == "ingress"]) == 1
        assert j.events[0][4]["source"] == "rpc"

    def test_pinned_journeys_survive_ring_eviction(self):
        b = self._board(capacity=4, pinned_capacity=4)
        shed = b"\xfe" * 32
        b.record(shed, "ingress", source="rpc")
        b.record(shed, "pool.evict", reason="capacity")
        for i in range(16):
            b.record(i.to_bytes(32, "big"), "ingress", source="rpc")
        assert b.evicted_total > 0
        j = b.get(shed)
        assert j is not None and j.pin_reason == "shed"

    def test_sampling_is_deterministic_in_the_hash(self):
        h = b"\x2a" * 32
        assert journey_sampled(h, 10_000)
        assert not journey_sampled(h, 0)
        first = journey_sampled(h, 500)
        assert all(journey_sampled(h, 500) == first for _ in range(8))
        # an unsampled happy-path tx STILL lands when a pin edge fires
        b = self._board(sample_per_10k=0)
        b.record(h, "ingress", source="rpc")
        assert b.get(h) is None
        b.record(h, "pool.evict", reason="capacity")
        assert b.get(h) is not None

    def test_max_events_truncates_but_keeps_terminal_edges(self):
        b = self._board(max_events=4)
        h = b"\x03" * 32
        b.record(h, "ingress", source="rpc")
        for i in range(8):
            b.record(h, "execute", lane="checked", index=i)
        b.record(h, "durable", block=9)
        j = b.get(h)
        assert len(j.events) == 5  # ingress + 3 executes + durable
        assert j.truncated == 5
        assert _edges(j)[-1] == "durable"
        rec = b.export(h)
        assert rec["truncatedEvents"] == 5

    def test_slow_tail_pins_on_durable(self):
        b = self._board(slow_ms=0.0)
        h = b"\x04" * 32
        b.record(h, "ingress", source="rpc")
        b.record(h, "durable", block=1)
        assert b.get(h).pin_reason == "slow"
        assert b.latencies_ms("durable")[0] >= 0.0

    def test_node_label_rides_the_stamp(self):
        b = self._board()
        h = b"\x05" * 32
        b.record(h, "ingress", source="rpc")
        with use_node("replica:r1"):
            b.record(h, "replica.visible", height=3)
        nodes = [e[2] for e in b.get(h).events]
        assert nodes == ["primary", "replica:r1"]

    def test_exemplar_trace_id_rides_the_exposition(self):
        """The histogram bucket line carries the owning trace id as an
        OpenMetrics-style exemplar — the link from a latency bucket to
        the flight-recorder ring that owns the journey's spans."""
        reg = MetricsRegistry()
        hist = reg.histogram(
            "t_commit_seconds", labels={"edge": "durable"}
        )
        hist.observe(0.012, exemplar="deadbeefcafe")
        text = reg.prometheus_text()
        assert text.count("# TYPE t_commit_seconds histogram") == 1
        assert 'trace_id="deadbeefcafe"' in text
