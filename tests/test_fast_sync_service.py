"""Fast-sync orchestration over real RLPx loopback peers.

The verdict-7 scenario: pivot selection by MEDIAN best number over >= N
peers, and a bounded-concurrency multi-peer node-download pool feeding
StateSyncer — with one of three serving peers STALLING mid-download
(request timeout -> blacklist -> work redistributed to the live peers).

Parity: FastSyncService.scala:184-273 (pivot), :537-667 (scheduler).
"""

import dataclasses
import threading
import time

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.network.host_service import HostService
from khipu_tpu.network.messages import (
    ETH_OFFSET,
    GET_NODE_DATA,
    Status,
)
from khipu_tpu.network.peer import PeerManager
from khipu_tpu.storage.compactor import verify_reachable
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.fast_sync_service import FastSyncError, FastSyncService
from khipu_tpu.sync.replay import ReplayDriver

SENDER_KEY = (11).to_bytes(32, "big")
SENDER = pubkey_to_address(privkey_to_pubkey(SENDER_KEY))
ALLOC = {SENDER: 10**24}

CFG = dataclasses.replace(
    fixture_config(chain_id=1),
    sync=SyncConfig(
        parallel_tx=False, tx_workers=2, commit_window_blocks=1,
        min_peers_to_choose_pivot=3, pivot_block_offset=3,
        nodes_per_request=16, peer_request_timeout=1.0,
    ),
)


def build_and_import(n_blocks=20):
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG, GenesisSpec(alloc=ALLOC)
    )
    blocks = []
    for n in range(1, n_blocks + 1):
        txs = [
            sign_transaction(
                Transaction(
                    n - 1, 10**9, 21_000,
                    bytes.fromhex("%040x" % (0xCAFE + n)), 1 + n,
                ),
                SENDER_KEY, chain_id=1,
            )
        ]
        blocks.append(builder.add_block(txs, coinbase=b"\xaa" * 20))
    bc = Blockchain(Storages(), CFG)
    bc.load_genesis(GenesisSpec(alloc=ALLOC))
    ReplayDriver(bc, CFG).replay(blocks)
    return bc, blocks


def make_status_factory(bc):
    def make():
        best = bc.best_block_number
        return Status(
            protocol_version=63, network_id=1,
            total_difficulty=bc.get_total_difficulty(best) or 0,
            best_hash=bc.get_hash_by_number(best),
            genesis_hash=bc.get_hash_by_number(0),
        )
    return make


@pytest.fixture
def cluster():
    """One source chain, three serving peers (one stallable), one
    syncing client connected to all three over RLPx loopback."""
    managers = []
    bc, blocks = build_and_import(20)
    stall = threading.Event()

    servers = []
    for i in range(3):
        priv = (0x5E0 + i).to_bytes(32, "big")
        m = PeerManager(priv, f"khipu-tpu/server{i}", make_status_factory(bc))
        HostService(bc).install(m)
        if i == 2:
            # peer 2 can be switched into a stall: accepts the request,
            # never answers (the reader thread sleeps through the
            # client's timeout window)
            real = m.handlers[ETH_OFFSET + GET_NODE_DATA]

            def stalling(body, _real=real):
                if stall.is_set():
                    time.sleep(5.0)
                    return None
                return _real(body)

            m.handlers[ETH_OFFSET + GET_NODE_DATA] = stalling
        port = m.listen()
        servers.append((m, port, privkey_to_pubkey(priv)))
        managers.append(m)

    syncer_bc = Blockchain(Storages(), CFG)
    syncer_bc.load_genesis(GenesisSpec(alloc=ALLOC))
    client = PeerManager(
        (0xC11).to_bytes(32, "big"), "khipu-tpu/syncer",
        make_status_factory(syncer_bc),
    )
    managers.append(client)
    for m, port, pub in servers:
        client.connect("127.0.0.1", port, pub)

    yield bc, blocks, syncer_bc, client, stall
    for m in managers:
        m.stop()


class TestFastSyncService:
    def test_pivot_is_median_minus_offset(self, cluster):
        bc, blocks, syncer_bc, client, stall = cluster
        svc = FastSyncService(syncer_bc, CFG, client)
        pivot = svc.choose_pivot()
        # all peers serve the same chain: median best = 20, offset 3
        assert pivot.number == 17
        assert pivot.state_root == blocks[16].header.state_root

    def test_pivot_requires_min_peers(self, cluster):
        bc, blocks, syncer_bc, client, stall = cluster
        # drop to 2 peers: below the configured minimum of 3
        client.peers[0].disconnect()
        svc = FastSyncService(syncer_bc, CFG, client)
        with pytest.raises(FastSyncError, match="peers"):
            svc.choose_pivot()

    def test_full_fast_sync_with_stalling_peer(self, cluster):
        bc, blocks, syncer_bc, client, stall = cluster
        logs = []
        svc = FastSyncService(syncer_bc, CFG, client, log=logs.append)
        stall.set()  # peer 2 stalls every node-data request
        state = svc.run()

        # the stalling peer was blacklisted and the download finished
        # from the other two
        assert svc.pool.blacklisted == 1
        assert client.blacklist.is_blacklisted(client.peers[2].remote_pub)
        assert state.downloaded_nodes > 20

        pivot_n = 20 - CFG.sync.pivot_block_offset
        # block data backfilled to the pivot
        assert syncer_bc.best_block_number == pivot_n
        assert (
            syncer_bc.get_hash_by_number(pivot_n)
            == blocks[pivot_n - 1].hash
        )
        # the downloaded state trie is COMPLETE at the pivot root
        root = blocks[pivot_n - 1].header.state_root
        report = verify_reachable(
            syncer_bc.storages.account_node_storage,
            syncer_bc.storages.storage_node_storage,
            syncer_bc.storages.evmcode_storage,
            root,
        )
        assert report.missing == 0
        # spot-check an account through the world at the pivot
        w = syncer_bc.get_world_state(root)
        assert w.get_balance(SENDER) > 0
        assert syncer_bc.storages.app_state.fast_sync_done
