"""EVM tests: words, programs, precompiles (external oracles where they
exist), bn128 self-consistency, and small bytecode programs through the
interpreter (parity targets vm/*.scala; SURVEY.md §4 plan)."""

import hashlib

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.config import fixture_config
from khipu_tpu.evm import dataword as dw
from khipu_tpu.evm.config import for_block
from khipu_tpu.evm.program import Program
from khipu_tpu.evm.vm import BlockEnv, MessageEnv, run
from khipu_tpu.ledger.world import BlockWorldState
from khipu_tpu.storage.datasource import MemoryNodeDataSource
from khipu_tpu.trie.mpt import MerklePatriciaTrie

CFG = for_block(1, fixture_config().blockchain)  # all forks active
FRONTIER = for_block(0, fixture_config(fork_block=10**9).blockchain)


def fresh_world():
    return BlockWorldState(
        MerklePatriciaTrie(MemoryNodeDataSource()),
        MemoryNodeDataSource(),
        MemoryNodeDataSource(),
    )


def run_code(code: bytes, config=CFG, gas: int = 1_000_000, world=None,
             input_data: bytes = b"", value: int = 0):
    world = world or fresh_world()
    env = MessageEnv(
        owner=b"\xcc" * 20,
        caller=b"\xdd" * 20,
        origin=b"\xdd" * 20,
        gas_price=1,
        value=value,
        input_data=input_data,
    )
    block = BlockEnv(1, 1000, 131072, 8_000_000, b"\xaa" * 20)
    return run(config, world, block, env, Program(code), gas)


class TestDataWord:
    def test_signed_edges(self):
        int_min = 1 << 255
        assert dw.sdiv(int_min, dw.MASK) == int_min  # INT_MIN / -1
        assert dw.sdiv(dw.from_signed(-7), dw.from_signed(2)) == dw.from_signed(-3)
        assert dw.smod(dw.from_signed(-7), dw.from_signed(2)) == dw.from_signed(-1)
        assert dw.smod(7, dw.from_signed(-2)) == 1

    def test_signextend(self):
        assert dw.signextend(0, 0xFF) == dw.MASK
        assert dw.signextend(0, 0x7F) == 0x7F
        assert dw.signextend(1, 0x80FF) == dw.from_signed(-0x7F01)

    def test_byte_and_sar(self):
        assert dw.byte_at(31, 0xAB) == 0xAB
        assert dw.byte_at(0, 0xAB << 248) == 0xAB
        assert dw.sar(1, dw.from_signed(-2)) == dw.from_signed(-1)
        assert dw.sar(300, dw.from_signed(-2)) == dw.MASK
        assert dw.sar(300, 5) == 0


class TestProgram:
    def test_jumpdest_analysis_skips_push_data(self):
        # PUSH2 0x5b5b JUMPDEST — only pc=3 is valid
        code = bytes([0x61, 0x5B, 0x5B, 0x5B])
        assert Program(code).valid_jumpdests == frozenset({3})

    def test_slice_pads(self):
        p = Program(b"\x01\x02")
        assert p.slice(1, 4) == b"\x02\x00\x00\x00"


class TestInterpreter:
    def test_add_mstore_return(self):
        # PUSH1 2 PUSH1 3 ADD PUSH1 0 MSTORE PUSH1 32 PUSH1 0 RETURN
        r = run_code(bytes.fromhex("600260030160005260206000f3"))
        assert r.error is None
        assert int.from_bytes(r.output, "big") == 5

    def test_invalid_jump_consumes_all_gas(self):
        r = run_code(bytes.fromhex("600456"))  # JUMP to 4 (no dest)
        assert r.error is not None
        assert r.gas_remaining == 0

    def test_revert_returns_data_and_gas(self):
        # PUSH1 0x2a PUSH1 0 MSTORE PUSH1 32 PUSH1 0 REVERT
        r = run_code(bytes.fromhex("602a60005260206000fd"))
        assert r.is_revert and r.error is None
        assert int.from_bytes(r.output, "big") == 0x2A
        assert r.gas_remaining > 0

    def test_revert_unavailable_pre_byzantium(self):
        r = run_code(bytes.fromhex("602a60005260206000fd"), config=FRONTIER)
        assert r.error is not None

    def test_sstore_and_refund(self):
        # store 1 at slot 0, then clear it within one frame
        code = bytes.fromhex("60016000556000600055")
        # Istanbul EIP-2200: reset-to-original-zero refunds
        # G_sstore_init - G_sstore_noop = 19200
        r = run_code(code)
        assert r.error is None
        assert r.refund == CFG.fees.G_sstore_init - CFG.fees.G_sstore_noop
        assert r.world.get_storage(b"\xcc" * 20, 0) == 0
        # legacy metering (pre-Istanbul): clear refunds R_sclear = 15000
        legacy = for_block(1, fixture_config(istanbul_block=10**9).blockchain)
        r2 = run_code(code, config=legacy)
        assert r2.error is None
        assert r2.refund == legacy.fees.R_sclear

    def test_sha3_matches_host_keccak(self):
        # PUSH32 "abcd"... MSTORE(0) ; SHA3(0, 4) ; return the digest
        code = bytes.fromhex(
            "7f" + (b"abcd" + b"\x00" * 28).hex()
            + "600052" + "60046000" + "20" + "60005260206000f3"
        )
        r = run_code(code)
        assert r.error is None
        assert r.output == keccak256(b"abcd")

    def test_exp_gas_fork_dependent(self):
        code = bytes.fromhex("61ffff600a0a00")  # 10 ** 0xffff then STOP
        r_new = run_code(code)
        r_old = run_code(code, config=FRONTIER)
        used_new = 1_000_000 - r_new.gas_remaining
        used_old = 1_000_000 - r_old.gas_remaining
        # EIP-160 raises G_expbyte 10 -> 50; exponent is 2 bytes
        assert used_new - used_old == 2 * (50 - 10)

    def test_static_violation(self):
        env_code = bytes.fromhex("6001600055")  # SSTORE
        world = fresh_world()
        env = MessageEnv(
            owner=b"\xcc" * 20, caller=b"\xdd" * 20, origin=b"\xdd" * 20,
            gas_price=1, value=0, input_data=b"", static=True,
        )
        block = BlockEnv(1, 1000, 131072, 8_000_000, b"\xaa" * 20)
        r = run(CFG, world, block, env, Program(env_code), 100_000)
        assert r.error is not None and "Static" in r.error

    def test_chainid_selfbalance_istanbul_only(self):
        code = bytes.fromhex("4660005260206000f3")  # CHAINID; return
        r = run_code(code)
        assert r.error is None
        assert int.from_bytes(r.output, "big") == CFG.chain_id
        assert run_code(code, config=FRONTIER).error is not None


class TestPrecompiles:
    def _call(self, addr_byte, data, config=CFG, gas=10_000_000):
        from khipu_tpu.evm.precompiles import get_precompile

        p = get_precompile(b"\x00" * 19 + bytes([addr_byte]), config)
        assert p is not None
        gas_fn, run_fn = p
        cost = gas_fn(data, config)
        assert cost <= gas
        return run_fn(data)

    def test_ecrecover_vector(self):
        from khipu_tpu.base.crypto.secp256k1 import (
            ecdsa_sign,
            privkey_to_pubkey,
            pubkey_to_address,
        )

        priv = b"\x46" * 32
        h = keccak256(b"hello")
        recid, r, s = ecdsa_sign(h, priv)
        data = (
            h
            + (27 + recid).to_bytes(32, "big")
            + r.to_bytes(32, "big")
            + s.to_bytes(32, "big")
        )
        out = self._call(1, data)
        assert out[12:] == pubkey_to_address(privkey_to_pubkey(priv))

    def test_ecrecover_bad_sig_empty_success(self):
        assert self._call(1, b"\x01" * 128) == b""

    def test_sha256_ripemd_identity(self):
        assert self._call(2, b"abc") == hashlib.sha256(b"abc").digest()
        # RIPEMD-160("abc") published digest
        assert self._call(3, b"abc")[12:].hex() == (
            "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc"
        )
        assert self._call(4, b"xyzzy") == b"xyzzy"

    def test_ripemd_pure_python_matches(self):
        from khipu_tpu.evm.ripemd160 import _ripemd160_py

        # empty-string published digest
        assert _ripemd160_py(b"").hex() == (
            "9c1185a5c5e9fc54612808977ee8f548b2258d31"
        )
        assert _ripemd160_py(b"abc").hex() == (
            "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc"
        )
        # multi-block input
        assert _ripemd160_py(b"a" * 1000) == __import__(
            "khipu_tpu.evm.ripemd160", fromlist=["ripemd160"]
        ).ripemd160(b"a" * 1000)

    def test_modexp(self):
        def pack(b, e, m):
            bb = b.to_bytes((b.bit_length() + 7) // 8 or 1, "big")
            eb = e.to_bytes((e.bit_length() + 7) // 8 or 1, "big")
            mb = m.to_bytes((m.bit_length() + 7) // 8 or 1, "big")
            return (
                len(bb).to_bytes(32, "big")
                + len(eb).to_bytes(32, "big")
                + len(mb).to_bytes(32, "big")
                + bb + eb + mb
            )

        assert self._call(5, pack(3, 5, 7)) == bytes([pow(3, 5, 7)])
        big = pack(2, 2**255, (1 << 256) - 189)
        assert int.from_bytes(self._call(5, big), "big") == pow(
            2, 2**255, (1 << 256) - 189
        )

    def test_blake2f_against_hashlib(self):
        """Drive the EIP-152 F function to a full blake2b-512 of 'abc'
        and compare with hashlib — a real external oracle."""
        import struct

        from khipu_tpu.evm.precompiles import _BLAKE2B_IV

        h = list(_BLAKE2B_IV)
        h[0] ^= 0x01010040  # depth=1, fanout=1, digest_length=64
        m = b"abc".ljust(128, b"\x00")
        data = (
            (12).to_bytes(4, "big")
            + struct.pack("<8Q", *h)
            + m
            + struct.pack("<2Q", 3, 0)
            + b"\x01"
        )
        out = self._call(9, data, config=CFG)
        assert out == hashlib.blake2b(b"abc").digest()

    def test_blake2f_bad_length(self):
        assert self._call(9, b"\x00" * 212) is None


G1 = (1, 2)
G2 = (
    (
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ),
    (
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ),
)


class TestBN128:
    def test_group_laws(self):
        from khipu_tpu.evm import bn128 as b

        assert b.on_g1(G1)
        assert b.on_g2_curve(G2)
        assert b.g1_add(G1, G1) == b.g1_mul(G1, 2)
        assert b.g1_add(b.g1_mul(G1, 5), b.g1_mul(G1, 7)) == b.g1_mul(G1, 12)
        assert b.g1_mul(G1, b.CURVE_ORDER) is None
        assert b.g2_mul(G2, b.CURVE_ORDER) is None

    def test_precompile_add_mul(self):
        from khipu_tpu.evm import bn128 as b

        two_g = b.g1_mul(G1, 2)
        data = b._write_g1(G1) + b._write_g1(G1)
        assert b.add_points(data) == b._write_g1(two_g)
        assert b.mul_point(
            b._write_g1(G1) + (2).to_bytes(32, "big")
        ) == b._write_g1(two_g)
        # identity encoding
        assert b.add_points(b"\x00" * 128) == b"\x00" * 64
        # not-on-curve rejected
        assert b.add_points(b"\x01" * 64 + b"\x00" * 64) is None

    def test_pairing_bilinearity(self):
        from khipu_tpu.evm import bn128 as b

        assert b.pairing(b.g2_mul(G2, 2), G1) == b.pairing(
            G2, b.g1_mul(G1, 2)
        )

    def test_pairing_precompile(self):
        from khipu_tpu.evm import bn128 as b

        def g2_bytes(q):
            (xr, xi), (yr, yi) = q
            return b"".join(
                v.to_bytes(32, "big") for v in (xi, xr, yi, yr)
            )

        # e(P, Q) * e(-P, Q) == 1
        data = (
            b._write_g1(G1) + g2_bytes(G2)
            + b._write_g1(b.g1_neg(G1)) + g2_bytes(G2)
        )
        assert b.pairing_check(data) == (1).to_bytes(32, "big")
        # single pair is not the identity
        one = b._write_g1(G1) + g2_bytes(G2)
        assert b.pairing_check(one) == (0).to_bytes(32, "big")
        # empty input is success (EIP-197)
        assert b.pairing_check(b"") == (1).to_bytes(32, "big")
        # malformed length fails
        assert b.pairing_check(b"\x00" * 191) is None


class TestBN128ExternalVectors:
    """EIP-196/197 anchors built ONLY from constants printed in the EIP
    texts (not from this implementation): the G1/G2 generator
    coordinates, the curve order n, the field prime p, and the
    universally published doubling 2*G1. A sign/limb/encoding bug in
    evm/bn128.py cannot survive these (the bilinearity tests above are
    self-consistent and could)."""

    # EIP-196 spec constants
    P = 21888242871839275222246405745257275088696311157297823662689037894645226208583  # noqa: E501
    N = 21888242871839275222246405745257275088548364400416034343698204186575808495617  # noqa: E501
    # 2*G1, derived IN THIS TEST MODULE from the spec constants alone
    # (affine doubling on y^2 = x^3 + 3 over F_p at G = (1,2)) — an
    # oracle independent of evm/bn128.py's Jacobian/tower code paths
    _LAM = (3 * pow(4, -1, P)) % P
    TWO_G_X = (_LAM * _LAM - 2) % P
    TWO_G_Y = (_LAM * (1 - TWO_G_X) - 2) % P
    assert (TWO_G_Y**2 - (TWO_G_X**3 + 3)) % P == 0
    # EIP-197 G2 generator (Fp2 elements c0 + c1*i); wire order is
    # imaginary-first: (x_c1, x_c0, y_c1, y_c0)
    G2X_C0 = 10857046999023057135944570762232829481370756359578518086990519993285655852781  # noqa: E501
    G2X_C1 = 11559732032986387107991004021392285783925812861821192530917403151452391805634  # noqa: E501
    G2Y_C0 = 8495653923123431417604973247489272438418190587263600148770280649306958101930  # noqa: E501
    G2Y_C1 = 4082367875863433681332203403145435568316851327593401208105741076214120093531  # noqa: E501

    def _call(self, addr_byte, data):
        from khipu_tpu.evm.precompiles import get_precompile

        p = get_precompile(b"\x00" * 19 + bytes([addr_byte]), CFG)
        gas_fn, run_fn = p
        gas_fn(data, CFG)
        return run_fn(data)

    @staticmethod
    def _w(*vals):
        return b"".join(v.to_bytes(32, "big") for v in vals)

    def test_ecadd_doubling_vector(self):
        out = self._call(0x6, self._w(1, 2, 1, 2))
        assert out == self._w(self.TWO_G_X, self.TWO_G_Y)

    def test_ecmul_by_two_vector(self):
        out = self._call(0x7, self._w(1, 2, 2))
        assert out == self._w(self.TWO_G_X, self.TWO_G_Y)

    def test_ecmul_by_group_order_is_infinity(self):
        out = self._call(0x7, self._w(1, 2, self.N))
        assert out == self._w(0, 0)

    def test_ecadd_inverse_points_is_infinity(self):
        # (1, 2) + (1, p-2) = O  — the negation rule comes from the
        # field prime, an EIP constant
        out = self._call(0x6, self._w(1, 2, 1, self.P - 2))
        assert out == self._w(0, 0)

    def test_ecadd_identity(self):
        assert self._call(0x6, self._w(1, 2, 0, 0)) == self._w(1, 2)

    def test_invalid_point_rejected(self):
        # (1, 3) is not on y^2 = x^3 + 3
        assert self._call(0x6, self._w(1, 3, 1, 2)) is None
        assert self._call(0x7, self._w(1, 3, 5)) is None

    def test_pairing_generator_vector(self):
        """e(G1, G2) * e(-G1, G2) == 1 with the SPEC's G2 coordinates in
        the SPEC's imaginary-first wire order — pins both the tower
        arithmetic and the Fp2 encoding convention."""
        g2 = self._w(self.G2X_C1, self.G2X_C0, self.G2Y_C1, self.G2Y_C0)
        data = self._w(1, 2) + g2 + self._w(1, self.P - 2) + g2
        assert self._call(0x8, data) == self._w(1)
        # a single generator pair is NOT the identity
        assert self._call(0x8, self._w(1, 2) + g2) == self._w(0)

    def test_pairing_bilinearity_cross_vector(self):
        """e(2*G1, G2) == e(G1, G2)^2 == e(G1, 2*G2): check via the
        product e(2G1, G2) * e(-G1, G2) * e(-G1, G2) == 1, using the
        published 2*G1 value rather than our own arithmetic."""
        g2 = self._w(self.G2X_C1, self.G2X_C0, self.G2Y_C1, self.G2Y_C0)
        neg_g1 = self._w(1, self.P - 2)
        data = (
            self._w(self.TWO_G_X, self.TWO_G_Y) + g2
            + neg_g1 + g2
            + neg_g1 + g2
        )
        assert self._call(0x8, data) == self._w(1)


def _deploy_helper(world, addr, runtime):
    """Install runtime code + account directly for frame-semantics tests."""
    from khipu_tpu.domain.account import Account

    world.save_account(addr, Account(nonce=1))
    world.save_code(addr, runtime)
    return world


class TestCallFrames:
    """Nested-frame semantics: context, rollback, returndata."""

    def test_delegatecall_uses_caller_storage(self):
        # B's runtime: SSTORE(0, 0x77)
        b_addr = b"\xbb" * 20
        writer = bytes.fromhex("6077600055")
        # A's runtime: DELEGATECALL(gas, B, 0,0,0,0) then return SLOAD(0)
        a_code = (
            bytes.fromhex("600060006000600073") + b_addr
            + bytes.fromhex("620186a0f4")  # gas 100000 DELEGATECALL
            + bytes.fromhex("5060005460005260206000f3")
        )
        world = fresh_world()
        _deploy_helper(world, b_addr, writer)
        r = run_code(a_code, world=world)
        assert r.error is None
        # the write landed in A's (owner's) storage, not B's
        assert int.from_bytes(r.output, "big") == 0x77
        assert r.world.get_storage(b"\xcc" * 20, 0) == 0x77
        assert r.world.get_storage(b_addr, 0) == 0

    def test_call_reverts_roll_back_but_gas_returns(self):
        # B: store then REVERT with 1 byte
        b_addr = b"\xbb" * 20
        reverter = bytes.fromhex("607760005560016000fd")
        # A: CALL B, then return (status << 8) | returndatasize
        a_code = (
            bytes.fromhex("6000600060006000600073") + b_addr
            + bytes.fromhex("620186a0f1")  # CALL
            + bytes.fromhex("6008") + bytes.fromhex("1b")  # shl status<<8
            + bytes.fromhex("3d17")  # | returndatasize
            + bytes.fromhex("60005260206000f3")
        )
        world = fresh_world()
        _deploy_helper(world, b_addr, reverter)
        r = run_code(a_code, world=world)
        assert r.error is None
        out = int.from_bytes(r.output, "big")
        assert out == (0 << 8) | 1  # status 0, returndata 1 byte
        # B's reverted SSTORE did not survive
        assert r.world.get_storage(b_addr, 0) == 0

    def test_nested_call_success_propagates_state(self):
        # C: SSTORE(1, 5)
        c_addr = b"\xcc\x01" + b"\x00" * 18
        c_code = bytes.fromhex("6005600155")
        # B: CALL C
        b_addr = b"\xbb" * 20
        b_code = (
            bytes.fromhex("6000600060006000600073") + c_addr
            + bytes.fromhex("61ea60f1") + bytes.fromhex("00")
        )
        world = fresh_world()
        _deploy_helper(world, b_addr, b_code)
        _deploy_helper(world, c_addr, c_code)
        # A: CALL B
        a_code = (
            bytes.fromhex("6000600060006000600073") + b_addr
            + bytes.fromhex("620186a0f1") + bytes.fromhex("00")
        )
        r = run_code(a_code, world=world)
        assert r.error is None
        assert r.world.get_storage(c_addr, 1) == 5  # two frames deep

    def test_create2_deterministic_address_and_redeploy_collision(self):
        from khipu_tpu.domain.transaction import create2_address

        # init code returning empty runtime: just STOP
        init = bytes.fromhex("00")
        # owner CREATE2(value=0, off=0, size=1, salt=9) with init 0x00
        code = (
            bytes.fromhex("7f") + init.ljust(32, b"\x00")  # PUSH32 init
            + bytes.fromhex("600052")
            + bytes.fromhex("6009600160006000f5")  # salt 9 size 1 off 0 val 0
            + bytes.fromhex("60005260206000f3")
        )
        world = fresh_world()
        r = run_code(code, world=world)
        assert r.error is None
        got = int.from_bytes(r.output, "big").to_bytes(32, "big")[12:]
        expect = create2_address(
            b"\xcc" * 20, (9).to_bytes(32, "big"), init
        )
        assert got == expect
        # second CREATE2 with the same salt on the same world: the
        # account exists with nonce 1 (EIP-161) -> collision -> 0
        r2 = run_code(code, world=r.world)
        assert int.from_bytes(r2.output, "big") == 0

    def test_staticcall_blocks_nested_write(self):
        # B writes storage; A STATICCALLs B -> status 0, no write
        b_addr = b"\xbb" * 20
        writer = bytes.fromhex("6077600055")
        a_code = (
            bytes.fromhex("600060006000600073") + b_addr
            + bytes.fromhex("620186a0fa")  # STATICCALL
            + bytes.fromhex("60005260206000f3")
        )
        world = fresh_world()
        _deploy_helper(world, b_addr, writer)
        r = run_code(a_code, world=world)
        assert r.error is None
        assert int.from_bytes(r.output, "big") == 0  # child failed
        assert r.world.get_storage(b_addr, 0) == 0

    def test_call_depth_limit(self):
        # self-recursive CALL: address CC..CC calls itself forever;
        # depth cap must terminate without error and without burning
        # the full gas on the deepest frames
        me = b"\xcc" * 20
        # push out_size..value zeros, PUSH20 me, GAS, CALL, return the
        # status word — gas on top of the operand stack
        code = (
            bytes.fromhex("6000600060006000600073") + me
            + bytes.fromhex("5af1")  # gas=GAS (63/64 per level)
            + bytes.fromhex("60005260206000f3")
        )
        world = fresh_world()
        _deploy_helper(world, me, code)
        # self-recursion terminates cleanly on gas (EIP-150's 63/64 rule
        # makes depth 1024 unreachable by gas alone — that was its point)
        r = run_code(code, world=world, gas=3_000_000)
        assert r.error is None
        # real recursion happened: the 63/64 cascade burned >150k
        assert r.gas_remaining < 2_850_000

        # the 1024-depth cap itself, tested directly: a frame ALREADY at
        # max depth must have its CALL return 0 with the child gas
        # refunded, not recurse or crash
        env = MessageEnv(
            owner=me, caller=b"\xdd" * 20, origin=b"\xdd" * 20,
            gas_price=1, value=0, input_data=b"", depth=1024,
        )
        block = BlockEnv(1, 1000, 131072, 8_000_000, b"\xaa" * 20)
        r2 = run(CFG, world.copy(), block, env, Program(code), 100_000)
        assert r2.error is None
        assert int.from_bytes(r2.output, "big") == 0  # CALL status 0
        # child gas came back: only the frame's own ops were paid
        assert r2.gas_remaining > 90_000


class TestEIP161TouchSurvivesRevert:
    """Mainnet #2,675,119 compat (EvmConfig.scala:111-118 +
    OpCode.scala:1425-1436): at exactly the configured patch block, a
    FAILED call to the RIPEMD-160 precompile still counts as a touch,
    so the pre-existing empty 0x..03 account is deleted at tx end; at
    every other post-EIP-161 block the revert erases the touch and the
    account survives. Checked on both VM backends."""

    RIPEMD = b"\x00" * 19 + b"\x03"

    def _run(self, patched: bool, backend: str):
        import dataclasses

        from khipu_tpu.base.crypto.secp256k1 import (
            privkey_to_pubkey,
            pubkey_to_address,
        )
        from khipu_tpu.domain.account import Account
        from khipu_tpu.domain.transaction import (
            Transaction,
            sign_transaction,
        )
        from khipu_tpu.evm import dispatch
        from khipu_tpu.ledger.ledger import execute_transaction
        from khipu_tpu.evm.config import for_block

        base = fixture_config(chain_id=1)
        bc = dataclasses.replace(
            base.blockchain, eip161_patch_block=100 if patched else 10**18
        )
        config = for_block(100, bc)
        assert config.eip161 and config.eip161_patch == patched

        key = (3).to_bytes(32, "big")
        sender = pubkey_to_address(privkey_to_pubkey(key))
        world = fresh_world()
        world.save_account(sender, Account(nonce=0, balance=10**18))
        # the empty ripemd account EXISTS (as it did on mainnet)
        world.save_account(self.RIPEMD, Account(nonce=0, balance=0))
        caller = b"\x77" * 20
        # CALL(gas=5, to=0x03, ...): 5 gas < ripemd's 600+ -> the
        # precompile frame fails with OOG
        code = bytes(
            [0x60, 0x00, 0x60, 0x00, 0x60, 0x00, 0x60, 0x00, 0x60, 0x00,
             0x60, 0x03, 0x60, 0x05, 0xF1, 0x00]
        )
        world.save_account(caller, Account(nonce=1))
        world.save_code(caller, code)
        world.persist(
            world.account_trie.source, world.storage_source,
            world.evmcode_source,
        )
        world.touched.clear()
        for cat in world.written:
            world.written[cat].clear()

        from khipu_tpu.evm.vm import BlockEnv

        block = BlockEnv(100, 1000, 131072, 8_000_000, b"\xaa" * 20)
        stx = sign_transaction(
            Transaction(0, 1, 100_000, caller, 0), key, chain_id=1
        )
        dispatch.set_backend(backend)
        try:
            r = execute_transaction(config, world, block, stx, sender)
        finally:
            dispatch.set_backend(None)
        assert r.status == 1  # the OUTER tx succeeds; only the sub-call failed
        return r.world.get_account(self.RIPEMD)

    @pytest.mark.parametrize("backend", ["python", "native"])
    def test_patch_block_deletes_empty_ripemd(self, backend):
        assert self._run(patched=True, backend=backend) is None

    @pytest.mark.parametrize("backend", ["python", "native"])
    def test_normal_block_reverts_the_touch(self, backend):
        acc = self._run(patched=False, backend=backend)
        assert acc is not None and acc.is_empty
