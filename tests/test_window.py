"""Block-window commit tests (ledger/window.py): N blocks, one batched
level-synchronous resolve, per-block root checks — the north-star
commit pipeline (BASELINE configs #1/#4)."""

import dataclasses

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import SyncConfig, fixture_config
from khipu_tpu.domain.block import Block
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import (
    Transaction,
    contract_address,
    sign_transaction,
)
from khipu_tpu.ledger.window import WindowMismatch
from khipu_tpu.storage.compactor import verify_reachable
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.replay import ReplayDriver

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(4)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18
MINER = b"\xaa" * 20

RUNTIME = bytes.fromhex("60005460005260206000f3")
_SS = bytes.fromhex("602a600055")
_COPY = bytes(
    [0x60, len(RUNTIME), 0x60, len(_SS) + 12, 0x60, 0, 0x39,
     0x60, len(RUNTIME), 0x60, 0, 0xF3]
)
INIT = _SS + _COPY + RUNTIME


def tx(i, nonce, to, value, gas=21000, payload=b""):
    return sign_transaction(
        Transaction(nonce, 10**9, gas, to, value, payload),
        KEYS[i], chain_id=1,
    )


@pytest.fixture(scope="module")
def chain():
    """5 blocks: deploy, cross-block call, second deploy + transfers."""
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG,
        GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}),
    )
    blocks = [
        builder.add_block(
            [tx(0, 0, None, 0, gas=300_000, payload=INIT)], coinbase=MINER
        )
    ]
    caddr = contract_address(ADDRS[0], 0)
    blocks.append(
        builder.add_block(
            [tx(0, 1, caddr, 0, gas=100_000), tx(1, 0, ADDRS[2], 123)],
            coinbase=MINER,
        )
    )
    blocks.append(
        builder.add_block(
            [tx(0, 2, None, 1000, gas=300_000, payload=INIT),
             tx(1, 1, ADDRS[3], 7)],
            coinbase=MINER,
        )
    )
    blocks.append(builder.add_block([tx(2, 0, ADDRS[0], 1)], coinbase=MINER))
    blocks.append(builder.add_block([tx(2, 1, ADDRS[0], 1)], coinbase=MINER))
    return blocks, caddr


def window_cfg(w, parallel=True):
    return dataclasses.replace(
        CFG, sync=SyncConfig(parallel_tx=parallel, commit_window_blocks=w)
    )


class TestWindowedReplay:
    def test_window1_device_path_uses_hasher(self, chain):
        """window=1 replay with a device hasher: the in-place root
        validation inside execute_block must flush with THAT hasher —
        not silently fall back to the eager host path (regression: the
        validate-then-persist fusion bypassed the batched commit)."""
        from khipu_tpu.trie.bulk import host_hasher

        calls = [0]

        def counting_hasher(msgs):
            calls[0] += 1
            return host_hasher(msgs)

        blocks, caddr = chain
        cfg = window_cfg(1)
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
        driver = ReplayDriver(bc, cfg, device_commit=True)
        driver.hasher = counting_hasher
        stats = driver.replay(blocks)
        assert stats.blocks == 5
        assert calls[0] > 0, "batched hasher never ran on the w=1 path"
        assert bc.get_header_by_number(5).hash == blocks[-1].hash

    @pytest.mark.parametrize("window", [2, 3, 5, 8])
    def test_windowed_equals_per_block(self, chain, window):
        """Any window size produces the identical chain state as the
        eager per-block path — and the persisted stores are complete
        (no node stranded in the staged dicts)."""
        blocks, caddr = chain
        cfg = window_cfg(window)
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
        stats = ReplayDriver(bc, cfg).replay(blocks)
        assert stats.blocks == 5
        head = blocks[-1].header
        assert bc.get_header_by_number(5).hash == blocks[-1].hash
        # persisted-store-only reads (no window session alive)
        fresh = Blockchain(bc.storages, cfg)
        world = fresh.get_world_state(head.state_root)
        assert world.get_storage(caddr, 0) == 42
        assert world.get_code(caddr) == RUNTIME
        report = verify_reachable(
            bc.storages.account_node_storage,
            bc.storages.storage_node_storage,
            bc.storages.evmcode_storage,
            head.state_root,
        )
        assert report.missing == 0

    def test_cross_block_reads_inside_window(self, chain):
        """Block 2 calls the contract block 1 deployed, with both inside
        ONE open window — the staged read-through is load-bearing."""
        blocks, _ = chain
        cfg = window_cfg(5, parallel=False)
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
        ReplayDriver(bc, cfg).replay(blocks)  # single 5-block window
        assert bc.get_header_by_number(5).hash == blocks[-1].hash

    def test_mismatch_pinpoints_block(self, chain):
        blocks, _ = chain
        cfg = window_cfg(4)
        bad = Block(
            dataclasses.replace(blocks[2].header, state_root=b"\x13" * 32),
            blocks[2].body,
        )
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
        with pytest.raises(WindowMismatch) as e:
            ReplayDriver(bc, cfg, validate_headers=False).replay(
                [blocks[0], blocks[1], bad]
            )
        assert e.value.number == 3

    def test_pre_byzantium_window_rejected(self, chain):
        blocks, _ = chain
        cfg = dataclasses.replace(
            fixture_config(chain_id=1, byzantium_block=10**9),
            sync=SyncConfig(commit_window_blocks=4),
        )
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
        with pytest.raises(ValueError, match="Byzantium"):
            ReplayDriver(bc, cfg, validate_headers=False).replay(blocks[:2])

    def test_balance_accounting_through_windows(self, chain):
        blocks, _ = chain
        cfg = window_cfg(3)
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
        ReplayDriver(bc, cfg).replay(blocks)
        root = blocks[-1].header.state_root
        # ADDRS[2]: +123 (block 2), then sent 1 wei twice with fees
        acc = bc.get_account(ADDRS[2], root)
        assert acc.balance == 1000 * ETH + 123 - 2 * (21000 * 10**9 + 1)
        assert acc.nonce == 2

    def test_epoch_reset_and_staged_prune(self, chain):
        """Pipelined session hygiene: collected windows drop their
        staged encodings (reads fall back through the resolved map to
        the persisted store), and the epoch reset rebuilds the session
        committer mid-replay without changing any result."""
        blocks, caddr = chain
        cfg = window_cfg(2)
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
        driver = ReplayDriver(bc, cfg)
        driver.session_epoch_blocks = 2  # reset after every window
        stats = driver.replay(blocks)
        assert stats.blocks == 5
        assert bc.get_header_by_number(5).hash == blocks[-1].hash
        # persisted-store-only reads still see everything
        fresh = Blockchain(bc.storages, cfg)
        world = fresh.get_world_state(blocks[-1].header.state_root)
        assert world.get_storage(caddr, 0) == 42
        report = verify_reachable(
            bc.storages.account_node_storage,
            bc.storages.storage_node_storage,
            bc.storages.evmcode_storage,
            blocks[-1].header.state_root,
        )
        assert report.missing == 0

    def test_collect_prunes_session_memory(self, chain):
        """After every window is persisted the committer's staged dict
        holds nothing (all placeholders resolved + pruned). Pruning now
        lands at the end of the persist stage (the staged collector
        split collect into rootcheck/admit + persist + save)."""
        from khipu_tpu.ledger.window import WindowCommitter

        blocks, _ = chain
        cfg = window_cfg(5)
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
        seen = []
        orig = WindowCommitter.persist

        def spy(self, job):
            r = orig(self, job)
            seen.append((len(self._staged), len(self._resolved_global)))
            return r

        WindowCommitter.persist = spy
        try:
            ReplayDriver(bc, cfg).replay(blocks)
        finally:
            WindowCommitter.persist = orig
        assert seen, "persist never ran"
        staged_left, resolved = seen[-1]
        assert staged_left == 0
        assert resolved > 0

    def test_mismatch_after_pipeline_overlap_persists_nothing(
        self, chain
    ):
        """A root mismatch in window N surfaces at collect(N) — after
        window N+1 already executed optimistically. Nothing from either
        window may reach the persisted block storage."""
        blocks, _ = chain
        cfg = window_cfg(2)
        bad = Block(
            dataclasses.replace(blocks[1].header, state_root=b"\x55" * 32),
            blocks[1].body,
        )
        bc = Blockchain(Storages(), cfg)
        bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
        with pytest.raises(WindowMismatch) as e:
            ReplayDriver(bc, cfg, validate_headers=False).replay(
                [blocks[0], bad, blocks[2], blocks[3]]
            )
        assert e.value.number == 2
        assert bc.get_header_by_number(1) is None
        assert bc.get_header_by_number(2) is None


def pipeline_cfg(w, depth, parallel=True):
    # adaptive_commit off: these tests assert the CONFIGURED commit
    # path and a fixed pipeline depth; the adaptive controller would
    # (correctly) fall back to host commit on the CPU backend and
    # resize the depth, defeating the assertions
    return dataclasses.replace(
        CFG,
        sync=SyncConfig(
            parallel_tx=parallel, commit_window_blocks=w,
            pipeline_depth=depth, adaptive_commit=False,
        ),
    )


def _fresh_chain(cfg):
    bc = Blockchain(Storages(), cfg)
    bc.load_genesis(GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}))
    return bc


class _DictStore:
    """Capture sink for compact(): records the reachable subgraph."""

    def __init__(self):
        self.nodes = {}

    def update(self, removes, upserts):
        self.nodes.update(upserts)


def _reachable(storages, root):
    """hash -> encoding of every node reachable from ``root`` — the
    bit-exactness comparand (two stores may differ in DEAD nodes the
    window split left behind; the live subgraph must be identical)."""
    from khipu_tpu.storage.compactor import compact

    acc, sto, code = _DictStore(), _DictStore(), _DictStore()
    report = compact(
        storages.account_node_storage,
        storages.storage_node_storage,
        storages.evmcode_storage,
        root, acc, sto, code,
    )
    assert report.missing == 0
    return acc.nodes, sto.nodes, code.nodes


class TestDeepPipeline:
    """Seal/collect ordering under the background collector
    (sync/replay._WindowCollector + ledger/window resolved-input
    tiles): depth sweep, cross-window bit-exactness, abort drains."""

    @pytest.mark.parametrize("depth", [1, 2, 4])
    def test_pipeline_depth_equals_per_block(self, chain, depth):
        """Any pipeline depth yields the identical persisted chain —
        collects run FIFO on the collector thread, roots all gate."""
        blocks, caddr = chain
        cfg = pipeline_cfg(2, depth)
        bc = _fresh_chain(cfg)
        stats = ReplayDriver(bc, cfg).replay(blocks)
        assert stats.blocks == 5
        assert bc.get_header_by_number(5).hash == blocks[-1].hash
        assert 0.0 <= stats.pipeline_occupancy <= 1.0
        assert "collect_bg" in stats.phases
        world = bc.get_world_state(blocks[-1].header.state_root)
        assert world.get_storage(caddr, 0) == 42
        report = verify_reachable(
            bc.storages.account_node_storage,
            bc.storages.storage_node_storage,
            bc.storages.evmcode_storage,
            blocks[-1].header.state_root,
        )
        assert report.missing == 0
        from khipu_tpu.sync.replay import PIPELINE_GAUGES

        assert PIPELINE_GAUGES["depth"] == depth
        assert PIPELINE_GAUGES["in_flight"] == 0

    @pytest.mark.slow  # ~60 s of XLA compile on a 1-core CPU host
    def test_cross_window_tiles_bit_exact_vs_finalize(self):
        """seal(N+1) while window N is STILL IN FLIGHT: refs into N
        ride the fused dispatch as resolved-input tiles. The collected
        state must be bit-exact with the one-window finalize() host
        path — same root AND byte-identical reachable node set."""
        import jax  # noqa: F401 — fused path needs a jax backend

        from khipu_tpu.domain.account import Account, address_key
        from khipu_tpu.ledger.window import WindowCommitter
        from khipu_tpu.trie.bulk import host_hasher
        from khipu_tpu.trie.deferred import _PLACEHOLDER_PREFIX
        from khipu_tpu.trie.mpt import EMPTY_TRIE_HASH

        def put_range(committer, rng):
            trie = committer.account_trie
            for i in rng:
                trie = trie.put(
                    address_key(i.to_bytes(20, "big")),
                    Account(nonce=i, balance=10**18 + i).encode(),
                )
            committer.account_trie = trie

        fused = WindowCommitter(
            Storages(), EMPTY_TRIE_HASH, hasher=host_hasher, fused=True
        )
        put_range(fused, range(30))
        job1 = fused.seal()
        # seal() is now the cheap driver close-out; the pack + dispatch
        # live in pack_and_dispatch (the collector's seal stage)
        fused.pack_and_dispatch(job1)
        assert job1.fused_job is not None, "fused path not taken"
        assert fused._inflight_rows, "window 1 not registered in flight"
        put_range(fused, range(30, 60))
        root_ref = fused.account_trie.force_hashed_root()
        job2 = fused.seal()
        fused.pack_and_dispatch(job2)  # packs against in-flight window 1
        # prove the cross-window mechanism was exercised: window 2's
        # packed encodings still embed window-1 placeholder bytes
        w1_phs = set(job1.to_resolve)
        refs = set()
        for enc in job2.to_resolve.values():
            pos = enc.find(_PLACEHOLDER_PREFIX)
            while pos >= 0:
                refs.add(enc[pos : pos + 32])
                pos = enc.find(_PLACEHOLDER_PREFIX, pos + 32)
        assert refs & w1_phs, "no cross-window refs — test is vacuous"
        fused.collect(job1)
        fused.collect(job2)
        assert not fused._inflight_rows
        real_root = fused._resolved_global[root_ref]

        host = WindowCommitter(
            Storages(), EMPTY_TRIE_HASH, hasher=host_hasher, fused=False
        )
        put_range(host, range(60))
        host_ref = host.account_trie.force_hashed_root()
        host.finalize()
        assert host._resolved_global[host_ref] == real_root
        assert _reachable(fused.storages, real_root) == _reachable(
            host.storages, real_root
        )

    def test_mid_pipeline_mismatch_drains_and_persists_nothing(
        self, chain
    ):
        """Corrupt root in the FIRST of five single-block windows at
        depth 4: the collector aborts, queued in-flight windows are
        dropped, the mismatch surfaces on the driver naming the block,
        and NO window persists to block storage."""
        blocks, _ = chain
        cfg = pipeline_cfg(1, 4)
        bad = Block(
            dataclasses.replace(
                blocks[0].header, state_root=b"\x66" * 32
            ),
            blocks[0].body,
        )
        bc = _fresh_chain(cfg)
        driver = ReplayDriver(bc, cfg, validate_headers=False)
        with pytest.raises(WindowMismatch) as e:
            driver.replay_windowed(
                iter([bad, blocks[1], blocks[2], blocks[3], blocks[4]]),
                1,
            )
        assert e.value.number == 1
        for n in range(1, 6):
            assert bc.get_header_by_number(n) is None
        from khipu_tpu.sync.replay import PIPELINE_GAUGES

        assert PIPELINE_GAUGES["in_flight"] == 0

    def test_live_placeholder_skipped_at_seal_names_index(self):
        """Satellite bugfix: a live placeholder with no staged encoding
        (the foreign-counter-range skip at seal) used to KeyError bare
        at collect; it must raise WindowPlaceholderError carrying the
        placeholder index."""
        from khipu_tpu.domain.account import Account, address_key
        from khipu_tpu.ledger.window import (
            WindowCommitter,
            WindowPlaceholderError,
        )
        from khipu_tpu.trie.deferred import _make_placeholder
        from khipu_tpu.trie.mpt import EMPTY_TRIE_HASH

        committer = WindowCommitter(Storages(), EMPTY_TRIE_HASH)
        trie = committer.account_trie
        for i in range(4):
            trie = trie.put(
                address_key(i.to_bytes(20, "big")),
                Account(nonce=i, balance=1).encode(),
            )
        committer.account_trie = trie
        job = committer.seal()
        ghost = _make_placeholder(10**9)  # a foreign session's index
        job.live[ghost] = 1
        with pytest.raises(WindowPlaceholderError) as e:
            committer.collect(job)
        assert e.value.index == 10**9
        assert str(10**9) in str(e.value)


class TestDeviceMirrorCommit:
    """Device-resident window commit (the mirror as commit target):
    bit-exactness vs the eager chain, the near-zero collect-phase d2h
    contract, and the retired-job device-buffer release."""

    def _device_replay(self, chain, cfg):
        from khipu_tpu.trie.bulk import host_hasher

        blocks, caddr = chain
        bc = _fresh_chain(cfg)
        driver = ReplayDriver(bc, cfg, device_commit=True)
        # fused seal path with the host keccak for the per-block root
        # gate (the interpreted device keccak is too slow on 1-core
        # CPU); the fused fixpoint program still runs on the backend
        driver.hasher = host_hasher
        return blocks, caddr, bc, driver

    def test_mirror_commit_bit_exact_and_collect_d2h_collapses(
        self, chain
    ):
        """THE tentpole contract: with the mirror as commit target the
        collect phase hauls only the per-block root digests over the
        tunnel (32 B x blocks) — the bulk mapping fetch moved to the
        async persist stage — and the persisted chain is bit-exact."""
        from khipu_tpu.observability.profiler import D2H, LEDGER

        cfg = pipeline_cfg(2, 2, parallel=False)
        blocks, caddr, bc, driver = self._device_replay(chain, cfg)
        LEDGER.enable()
        LEDGER.reset()
        try:
            stats = driver.replay(blocks)
            per_phase = LEDGER.phase_bytes_per_block()
        finally:
            LEDGER.disable()
        assert stats.blocks == 5
        assert bc.get_header_by_number(5).hash == blocks[-1].hash
        # state correct through the mirror read path AND after spill
        world = bc.get_world_state(blocks[-1].header.state_root)
        assert world.get_storage(caddr, 0) == 42
        report = verify_reachable(
            bc.storages.account_node_storage,
            bc.storages.storage_node_storage,
            bc.storages.evmcode_storage,
            blocks[-1].header.state_root, verify_hashes=True,
        )
        assert report.missing == 0 and report.corrupt == 0
        # collect-phase d2h collapses to the 32 B/block rootcheck;
        # the big digest fetch now bills to the persist stage
        collect_d2h = per_phase.get("collect", {}).get(D2H, 0)
        assert 0 < collect_d2h <= 256, per_phase
        persist_d2h = per_phase.get("persist", {}).get(D2H, 0)
        assert persist_d2h > collect_d2h, per_phase
        # the mirror took the window admits and stayed claim-consistent
        mirror = driver._mirror
        assert mirror is not None
        assert mirror.resident_count > 0
        assert mirror.verify() == 0

    def test_retired_jobs_release_device_buffers(self, chain):
        """Satellite contract: every fused job frees its encoding
        buffers at mirror admit (collect stage) and its digest buffers
        once the window retires beyond the pipeline — HBM stays
        O(in-flight windows), not O(replayed chain)."""
        from khipu_tpu.trie import fused as fused_mod

        released, encs_released = [], []
        orig_release = fused_mod.FusedJob.release
        orig_encs = fused_mod.FusedJob.release_encs

        def spy_release(self):
            released.append(self)
            return orig_release(self)

        def spy_encs(self):
            encs_released.append(self)
            return orig_encs(self)

        fused_mod.FusedJob.release = spy_release
        fused_mod.FusedJob.release_encs = spy_encs
        try:
            cfg = pipeline_cfg(2, 2, parallel=False)
            blocks, _caddr, bc, driver = self._device_replay(chain, cfg)
            stats = driver.replay(blocks)
        finally:
            fused_mod.FusedJob.release = orig_release
            fused_mod.FusedJob.release_encs = orig_encs
        assert stats.blocks == 5
        # 5 blocks / window=2 -> 3 windows, each encs-released at admit
        # and fully released by the end-of-replay retire drain
        assert len(encs_released) == 3
        assert len(released) == 3
        for job in released:
            assert job.digests is None and job.encs is None
