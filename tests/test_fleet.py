"""Replica fleet (serving/replica.py + serving/fleet.py +
serving/router.py — docs/serving.md "Replica fleet").

The headline guarantees: a consistent-read token is NEVER answered
with state older than its height — across replica failover (the
wait-or-redirect path counts the redirect) and across a PR 15 reorg
(a retracted token re-anchors to the fork ancestor); a primary reorg
MIRRORS through each replica's own journaled switch, so ``removed:
true`` retractions and adopted-block redelivery reach every replica's
FilterManager exactly once; and a 120-seed kill sweep over the
``replica.tail`` / ``fleet.route`` seam pair lands every replica at a
hash-exact prefix of the primary chain, converging to the full chain
once the tail resumes.
"""

import dataclasses
import random

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.chaos import FaultPlan, FaultRule, InjectedDeath, active
from khipu_tpu.config import ServingConfig, SyncConfig, fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.jsonrpc import EthService, JsonRpcServer
from khipu_tpu.serving.fleet import FleetRouter
from khipu_tpu.serving.readview import ReadView
from khipu_tpu.serving.replica import PrimaryFeed, ReplicaDriver
from khipu_tpu.serving.router import ReadToken, pick2
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.reorg import ReorgManager
from khipu_tpu.sync.replay import ReplayDriver, ReplayStats

pytestmark = pytest.mark.chaos

CFG = dataclasses.replace(
    fixture_config(chain_id=1),
    sync=SyncConfig(commit_window_blocks=1, parallel_tx=False),
    serving=ServingConfig(
        replica_poll_interval=0.002, ryw_wait_s=0.5
    ),
)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(4)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18
ALLOC = {a: 1000 * ETH for a in ADDRS}
GEN = GenesisSpec(alloc=ALLOC)
MINER_A = b"\xaa" * 20
MINER_B = b"\xbb" * 20


def _tx(i, nonce, to, value):
    return sign_transaction(
        Transaction(nonce, 10**9, 21_000, to, value),
        KEYS[i], chain_id=1,
    )


def build(n, diverge_at=None, value_off=0):
    """Consensus-true chain of ``n`` transfer blocks; from
    ``diverge_at`` on, coinbase and tx values change (same senders
    and nonces — a real competing branch, not a replay)."""
    builder = ChainBuilder(Blockchain(Storages(), CFG), CFG, GEN)
    blocks, nonces = [], [0, 0, 0, 0]
    for k in range(n):
        i = k % 4
        diverged = diverge_at is not None and k >= diverge_at
        blocks.append(builder.add_block(
            [_tx(i, nonces[i], ADDRS[(i + 1) % 4],
                 100 + k + (value_off if diverged else 0))],
            coinbase=MINER_B if diverged else MINER_A,
            timestamp=10 * (k + 1),
        ))
        nonces[i] += 1
    return builder.blockchain, blocks


@pytest.fixture(scope="module")
def chains():
    """(base 10, fork 10 diverging at 5) for the router tests plus a
    smaller (base 6, fork 8 diverging at 3) pair for the seed sweep —
    built once; every node under test re-imports through the
    validated replay path."""
    base_bc, base = build(10)
    fork_bc, fork = build(10, diverge_at=5, value_off=1000)
    sweep_base_bc, sweep_base = build(6)
    sweep_fork_bc, sweep_fork = build(8, diverge_at=3, value_off=500)
    return {
        "base_bc": base_bc, "base": base,
        "fork_bc": fork_bc, "fork": fork,
        "sweep_base_bc": sweep_base_bc, "sweep_base": sweep_base,
        "sweep_fork_bc": sweep_fork_bc, "sweep_fork": sweep_fork,
    }


class _Primary:
    """A full primary node (store + replay driver + journaled reorg +
    RPC service/server) synced through ``blocks[:upto]``."""

    def __init__(self, blocks, upto, config=CFG):
        self.bc = Blockchain(Storages(), config)
        self.bc.load_genesis(GEN)
        self.view = ReadView(self.bc)
        self.driver = ReplayDriver(self.bc, config, read_view=self.view)
        self.reorg = ReorgManager(
            self.bc, config, driver=self.driver, read_view=self.view
        )
        self.service = EthService(
            self.bc, config, read_view=self.view,
            reorg_manager=self.reorg,
        )
        self.server = JsonRpcServer(self.service)
        self.stats = ReplayStats()
        for b in blocks[:upto]:
            self.driver._execute_and_insert(b, self.stats)
        self.feed = PrimaryFeed(self.bc)

    def import_block(self, block):
        self.driver._execute_and_insert(block, self.stats)


def _tail_until(replica, number, block_hash=None, limit=200):
    """Drive ``tail_once`` until the replica serves ``number`` (and,
    when given, the exact hash there). Bounded: a wedged tail fails
    the test instead of hanging it."""
    for _ in range(limit):
        h = replica.blockchain.get_header_by_number(number)
        if h is not None and (block_hash is None or h.hash == block_hash):
            return
        replica.tail_once()
    raise AssertionError(
        f"replica {replica.name} never reached block {number}"
    )


def _read(router, token=None, method="eth_blockNumber", params=()):
    req = {
        "jsonrpc": "2.0", "id": 1,
        "method": method, "params": list(params),
    }
    if token is not None:
        req["khipuToken"] = token
    return router.handle(req)


# ------------------------------------------------------- token codec


def test_token_roundtrip_with_hash():
    t = ReadToken(chain_id=1, number=7, block_hash=b"\x11" * 32)
    assert ReadToken.decode(t.encode()) == t


def test_token_roundtrip_without_hash():
    t = ReadToken(chain_id=5, number=2**40, block_hash=None)
    assert ReadToken.decode(t.encode()) == t


def test_token_garbage_downgrades_to_none():
    # malformed tokens must degrade the request to tokenless routing,
    # never error it — decode returns None for every shape of garbage
    for raw in (None, 123, "", "zz", "0x", "0xzz",
                "0x" + "ab" * 20,   # 20-byte body: neither 16 nor 48
                "0x" + "ab" * 47):
        assert ReadToken.decode(raw) is None


# ------------------------------------------------------------- pick2


def test_pick2_excludes_zero_weight():
    rng = random.Random(0)
    for _ in range(100):
        got = pick2(rng, ["dead", "live"],
                    weight_fn=lambda c: 0.0 if c == "dead" else 1.0,
                    load_fn=lambda c: 0)
        assert got == "live"
    assert pick2(rng, ["a", "b"], lambda c: 0.0, lambda c: 0) is None
    assert pick2(rng, [], lambda c: 1.0, lambda c: 0) is None


def test_pick2_lower_load_wins():
    rng = random.Random(0)
    loads = {"a": 5, "b": 1}
    for _ in range(100):
        assert pick2(rng, ["a", "b"], lambda c: 1.0,
                     loads.__getitem__) == "b"


def test_pick2_health_weights_traffic():
    rng = random.Random(0)
    weights = {"healthy": 1.0, "sick": 0.05}
    picks = [pick2(rng, ["healthy", "sick"], weights.__getitem__,
                   lambda c: 0) for _ in range(400)]
    # both draws fall on the healthy replica most rounds; the sick one
    # still gets SOME traffic (weighted, not excluded)
    assert picks.count("healthy") > 300
    assert picks.count("sick") > 0


# ------------------------------------------------------- replica tail


def test_replica_tails_to_primary_head(chains):
    p = _Primary(chains["base"], 8)
    r = ReplicaDriver("tail", p.feed, CFG, GEN)
    _tail_until(r, 8, chains["base"][7].header.hash)
    assert r.blockchain.best_block_number == 8
    for n in range(0, 9):
        assert (r.blockchain.get_header_by_number(n).hash
                == p.feed.hash_of(n))
    assert r.lag_blocks() == 0
    # state parity, not just headers: the replica re-executed, so it
    # serves the same balances the primary does
    addr = "0x" + ADDRS[0].hex()
    assert (r.service.eth_getBalance(addr, "latest")
            == p.service.eth_getBalance(addr, "latest"))


def test_replica_rejects_mismatched_genesis(chains):
    p = _Primary(chains["base"], 4)
    other = GenesisSpec(alloc={ADDRS[0]: 7 * ETH})
    with pytest.raises(ValueError, match="genesis"):
        ReplicaDriver("bad-gen", p.feed, CFG, other)


def test_reorg_retraction_reaches_lagging_replica_filter(chains):
    """A primary switch must reach a LAGGING replica's FilterManager
    through the replica's own mirrored switch: the adopted blocks are
    redelivered to its block filter exactly once, and retracted log
    state rewinds — no duplicate retraction on later polls."""
    base, fork = chains["base"], chains["fork"]
    p = _Primary(base, 8)
    r = ReplicaDriver("lag", p.feed, CFG, GEN)
    _tail_until(r, 8, base[7].header.hash)
    fm = r.service._filter_manager
    fid = fm.new_block_filter()
    assert fm.changes(fid) == []  # installed at the tip: no backlog
    # the primary adopts the fork while the replica is NOT polling —
    # it only learns of the switch on its next manual tail pass
    p.reorg.switch(5, fork[5:])
    assert p.bc.best_block_number == 10
    _tail_until(r, 10, fork[9].header.hash)
    assert r.switches_mirrored == 1
    # blocks 1..5 are shared, so exactly the adopted suffix redelivers
    assert fm.changes(fid) == [b.header.hash for b in fork[5:]]
    assert fm.changes(fid) == []  # once — no duplicate retraction
    # and the replica's canonical chain is the fork, height for height
    for n in range(0, 11):
        assert (r.blockchain.get_header_by_number(n).hash
                == p.feed.hash_of(n))


# ---------------------------------------------- failover + RYW tokens


def test_failover_mid_poll_zero_stale_reads(chains):
    """Token-bearing reads keep their floor across a replica kill
    mid-polling: every response's height >= the echoed token's
    height, with zero stale reads before, during, and after the
    failover."""
    base = chains["base"]
    p = _Primary(base, 5)
    r1 = ReplicaDriver("f1", p.feed, CFG, GEN).start()
    r2 = ReplicaDriver("f2", p.feed, CFG, GEN).start()
    router = FleetRouter(
        p.server, [r1, r2], reorg_manager=p.reorg, seed=1
    )
    try:
        assert r1.ensure_height(5, 5.0) and r2.ensure_height(5, 5.0)
        token = None
        after_kill = 0
        for step in range(12):
            if step in (4, 6, 8, 10):  # primary keeps committing
                p.import_block(base[5 + (step - 4) // 2])
            if step == 6:  # kill one replica mid-poll
                r1.kill()
                assert not r1.alive()
            resp = _read(router, token=token)
            assert "error" not in resp
            floor = ReadToken.decode(token).number if token else 0
            got = int(resp["result"], 16)
            assert got >= floor, (
                f"stale read at step {step}: {got} < token {floor}"
            )
            token = resp["khipuToken"]
            if step > 6:
                after_kill += 1
        assert after_kill >= 5 and r2.alive()
        # the surviving replica converged on the primary's chain
        assert r2.ensure_height(9, 5.0)
        assert r2.has_block(9, base[8].header.hash)
    finally:
        r1.kill()
        r2.kill()


def test_ryw_redirect_counted_on_lagging_replica(chains):
    """Deterministic wait-or-redirect: an ALIVE replica parked on a
    long poll interval lags the primary; a token at the primary's
    height cannot be honored within the RYW budget, so the read
    redirects to the primary and the redirect is counted. A tokenless
    read meanwhile happily serves the replica's older height."""
    base = chains["base"]
    lag_cfg = dataclasses.replace(
        CFG, serving=ServingConfig(
            replica_poll_interval=60.0, ryw_wait_s=0.02
        ),
    )
    p = _Primary(base, 8, config=lag_cfg)
    r = ReplicaDriver("lagger", p.feed, lag_cfg, GEN).start()
    router = FleetRouter(p.server, [r], reorg_manager=p.reorg, seed=2)
    try:
        assert r.ensure_height(8, 5.0)
        # the replica's tail is now asleep for 60s; advance the primary
        p.import_block(base[8])
        p.import_block(base[9])
        assert r.lag_blocks() == 2 and r.alive()
        # tokenless: the replica serves its own (older) height
        resp = _read(router)
        assert int(resp["result"], 16) == 8
        assert router.reads_replica == 1
        # token at the primary head: floor 10 > replica head 8, the
        # 20ms budget cannot cover a 60s poll -> redirect + count
        token = ReadToken(1, 10, base[9].header.hash).encode()
        resp = _read(router, token=token)
        assert int(resp["result"], 16) == 10  # primary served
        assert router.ryw_redirects == 1
        # the fresh token re-minted from the primary carries height 10
        assert ReadToken.decode(resp["khipuToken"]).number == 10
    finally:
        r.kill()


def test_retracted_token_reanchors_to_fork_ancestor(chains):
    """A token anchored to a block the reorg threw away re-anchors to
    the fork ancestor (counted), so any caught-up replica can serve
    it — the write it certified is gone, and 'no older than the
    ancestor' is the strongest honest floor left."""
    base, fork = chains["base"], chains["fork"]
    p = _Primary(base, 8)
    r = ReplicaDriver("re-anchor", p.feed, CFG, GEN)
    router = FleetRouter(p.server, [r], reorg_manager=p.reorg, seed=3)
    _tail_until(r, 8, base[7].header.hash)
    stale = ReadToken(1, 7, base[6].header.hash).encode()
    p.reorg.switch(5, fork[5:])
    _tail_until(r, 10, fork[9].header.hash)
    # the replica never started a thread -> not alive -> pick2 skips
    # it; start it so liveness-weighted routing sees a live candidate
    r.start()
    try:
        resp = _read(router, token=stale)
        assert "error" not in resp
        assert router.tokens_reanchored == 1
        assert router.ryw_redirects == 0  # ancestor floor: no redirect
        assert router.snapshot()["lastAncestor"] == 5
        # a foreign-chain token is ignored outright, not re-anchored
        foreign = ReadToken(999, 7, base[6].header.hash).encode()
        _read(router, token=foreign)
        assert router.tokens_reanchored == 1
    finally:
        r.kill()


# --------------------------------------------------- 120-seed sweep

SWEEP_SITES = ["replica.tail", "fleet.route"]


@pytest.fixture(scope="module")
def sweep_primaries(chains):
    """The two feed states a sweep replica tails: the base chain at 6
    and the fork chain at 8. Swapping a replica's feed from one to
    the other IS a primary reorg as far as the follower can tell —
    same divergence walk, same mirrored switch — without rebuilding a
    primary per seed."""
    before = _Primary(chains["sweep_base"], 6)
    after = _Primary(chains["sweep_fork"], 8)
    return before, after


def _assert_prefix_of(replica, feed):
    """The dead-anywhere invariant: every block the replica holds is
    the feed's block at that height — a hash-exact prefix, never a
    mix of branches past what the feed serves."""
    best = replica.blockchain.best_block_number
    for n in range(0, best + 1):
        h = replica.blockchain.get_header_by_number(n)
        assert h is not None and h.hash == feed.hash_of(n), (
            f"replica diverges from primary at block {n}"
        )


def _run_tail_seed(seed, after, sweep_primaries, chains):
    """Catch up on the base feed, live through a feed switch (the
    primary reorg), with one injected death staggered through the
    ``replica.tail`` seam. Returns True when the death fired."""
    before, after_p = sweep_primaries
    r = ReplicaDriver(f"sweep-{seed}", before.feed, CFG, GEN)
    plan = FaultPlan(seed=seed, rules=[
        FaultRule("replica.tail", kind="die", times=1, after=after),
    ])
    died = False
    try:
        with active(plan):
            _tail_until(r, 6, chains["sweep_base"][5].header.hash)
            r.feed = after_p.feed  # the primary reorged under us
            _tail_until(r, 8, chains["sweep_fork"][7].header.hash)
    except InjectedDeath:
        died = True
        # fail-stop at the seam: whatever landed must be a prefix of
        # ONE of the primary states (never an interleaving)
        feed = (before.feed
                if r.blockchain.best_block_number <= 6
                and r.switches_mirrored == 0
                and r.blockchain.get_header_by_number(
                    min(r.blockchain.best_block_number, 4)
                ).hash == before.feed.hash_of(
                    min(r.blockchain.best_block_number, 4))
                else r.feed)
        _assert_prefix_of(r, feed)
    # recovery: the tail resumes (plan inactive) and must converge on
    # the current primary chain exactly
    r.feed = after_p.feed
    _tail_until(r, 8, chains["sweep_fork"][7].header.hash)
    _assert_prefix_of(r, after_p.feed)
    assert r.blockchain.best_block_number == 8
    return died


def _run_route_seed(seed, after, fleet):
    """One injected death inside ``fleet.route``: the in-flight
    request dies, the router does not — counters drain to zero and
    the next read succeeds. Returns True when the death fired."""
    router, replica = fleet
    plan = FaultPlan(seed=seed, rules=[
        FaultRule("fleet.route", kind="die", times=1, after=after),
    ])
    died = False
    try:
        with active(plan):
            for _ in range(8):
                resp = _read(router)
                assert "error" not in resp
    except InjectedDeath:
        died = True
    # the seam fires BEFORE inflight tracking: nothing leaks
    assert sum(router._inflight.values()) == 0
    resp = _read(router)
    assert "error" not in resp
    assert ReadToken.decode(resp["khipuToken"]) is not None
    return died


def test_fleet_seeded_kill_sweep(chains, sweep_primaries):
    """120 seeds staggered across the ``replica.tail`` /
    ``fleet.route`` seam pair. Every ``replica.tail`` death lands the
    replica at a hash-exact prefix of a primary chain state and
    recovery converges on the fork tip; every ``fleet.route`` death
    kills one request, never the router. The stagger must actually
    exercise both outcomes: > 20 killed and > 20 survived.

    The two seam groups run back to back (same seed/stagger layout):
    fault plans are process-global while active, so the route fleet —
    whose replica runs a background tail thread full of
    ``replica.tail`` hits — must not exist while a tail seed's single
    ``times=1`` death is armed, or the poller races the sweep replica
    for it."""
    killed = survived = 0
    stagger = {
        seed: (seed // len(SWEEP_SITES)) % 16 for seed in range(120)
    }
    for seed, after in stagger.items():
        if SWEEP_SITES[seed % len(SWEEP_SITES)] != "replica.tail":
            continue
        if _run_tail_seed(seed, after, sweep_primaries, chains):
            killed += 1
        else:
            survived += 1
    p = _Primary(chains["base"], 8)
    r = ReplicaDriver("route-r", p.feed, CFG, GEN).start()
    router = FleetRouter(p.server, [r], reorg_manager=p.reorg, seed=7)
    try:
        assert r.ensure_height(8, 5.0)
        for seed, after in stagger.items():
            if SWEEP_SITES[seed % len(SWEEP_SITES)] != "fleet.route":
                continue
            if _run_route_seed(seed, after, (router, r)):
                killed += 1
            else:
                survived += 1
    finally:
        r.kill()
    assert killed > 20 and survived > 20, (killed, survived)
