"""JSON-RPC + simulation + tx pool + keystore tests (parity targets
jsonrpc/EthService.scala, Ledger.simulateTransaction:166-191,
PendingTransactionsService.scala:66, keystore/KeyStore.scala:31)."""

import json
import urllib.request

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import (
    Transaction,
    contract_address,
    sign_transaction,
)
from khipu_tpu.jsonrpc import EthService, JsonRpcServer
from khipu_tpu.keystore import KeyStore, KeyStoreError, decrypt_key, encrypt_key
from khipu_tpu.ledger.simulate import estimate_gas, simulate_call
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.txpool import PendingTransactionsPool

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(3)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18

RUNTIME = bytes.fromhex("60005460005260206000f3")
_SS = bytes.fromhex("602a600055")
_COPY = bytes(
    [0x60, len(RUNTIME), 0x60, len(_SS) + 12, 0x60, 0, 0x39,
     0x60, len(RUNTIME), 0x60, 0, 0xF3]
)
INIT = _SS + _COPY + RUNTIME


@pytest.fixture(scope="module")
def chain():
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG,
        GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}),
    )
    builder.add_block(
        [sign_transaction(
            Transaction(0, 10**9, 300_000, None, 0, INIT), KEYS[0], chain_id=1
        )],
        coinbase=b"\xaa" * 20,
    )
    builder.add_block(
        [sign_transaction(
            Transaction(1, 10**9, 21_000, ADDRS[1], 5 * ETH), KEYS[0], chain_id=1
        )],
        coinbase=b"\xaa" * 20,
    )
    return builder.blockchain


@pytest.fixture(scope="module")
def service(chain):
    return EthService(chain, CFG)


class TestSimulate:
    def test_eth_call_reads_contract(self, chain):
        caddr = contract_address(ADDRS[0], 0)
        header = chain.get_header_by_number(2)
        r = simulate_call(
            chain.get_world_state, header, CFG, to=caddr, gas=100_000
        )
        assert r.ok
        assert int.from_bytes(r.output, "big") == 42

    def test_simulation_discards_writes(self, chain):
        header = chain.get_header_by_number(2)
        before = chain.get_account(ADDRS[1], header.state_root).balance
        simulate_call(
            chain.get_world_state, header, CFG,
            sender=ADDRS[0], to=ADDRS[1], value=ETH, gas=30_000,
        )
        assert chain.get_account(ADDRS[1], header.state_root).balance == before

    def test_estimate_gas_transfer(self, chain):
        header = chain.get_header_by_number(2)
        est = estimate_gas(
            chain.get_world_state, header, CFG,
            sender=ADDRS[0], to=ADDRS[1], value=1,
        )
        assert est == 21_000

    def test_estimate_gas_contract_call(self, chain):
        caddr = contract_address(ADDRS[0], 0)
        header = chain.get_header_by_number(2)
        est = estimate_gas(
            chain.get_world_state, header, CFG, to=caddr
        )
        assert est > 21_000
        # the estimate is minimal-sufficient: one less unit fails
        r_ok = simulate_call(
            chain.get_world_state, header, CFG, to=caddr, gas=est
        )
        r_low = simulate_call(
            chain.get_world_state, header, CFG, to=caddr, gas=est - 1
        )
        assert r_ok.ok and not r_low.ok


class TestEthService:
    def test_basic_queries(self, service):
        assert service.eth_blockNumber() == "0x2"
        assert service.eth_chainId() == "0x1"
        bal = service.eth_getBalance("0x" + ADDRS[1].hex())
        assert int(bal, 16) == 1005 * ETH
        assert service.eth_getTransactionCount("0x" + ADDRS[0].hex()) == "0x2"
        assert service.net_version() == "1"
        assert service.web3_sha3("0x") == "0x" + keccak256(b"").hex()

    def test_block_and_tx_queries(self, service, chain):
        block = service.eth_getBlockByNumber("latest", True)
        assert block["number"] == "0x2"
        assert len(block["transactions"]) == 1
        tx_hash = block["transactions"][0]["hash"]
        tx = service.eth_getTransactionByHash(tx_hash)
        assert tx["blockNumber"] == "0x2"
        receipt = service.eth_getTransactionReceipt(tx_hash)
        assert receipt["status"] == "0x1"
        assert receipt["gasUsed"] == hex(21_000)
        by_hash = service.eth_getBlockByHash(block["hash"])
        assert by_hash["number"] == "0x2"

    def test_code_and_storage(self, service):
        caddr = "0x" + contract_address(ADDRS[0], 0).hex()
        assert service.eth_getCode(caddr) == "0x" + RUNTIME.hex()
        slot0 = service.eth_getStorageAt(caddr, "0x0")
        assert int(slot0, 16) == 42

    def test_eth_call_and_estimate(self, service):
        caddr = "0x" + contract_address(ADDRS[0], 0).hex()
        out = service.eth_call({"to": caddr})
        assert int(out, 16) == 42
        est = service.eth_estimateGas(
            {"from": "0x" + ADDRS[0].hex(), "to": "0x" + ADDRS[1].hex(),
             "value": "0x1"}
        )
        assert est == hex(21_000)

    def test_send_raw_transaction(self, service):
        stx = sign_transaction(
            Transaction(2, 10**9, 21_000, ADDRS[2], 7), KEYS[0], chain_id=1
        )
        h = service.eth_sendRawTransaction("0x" + stx.encode().hex())
        assert h == "0x" + stx.hash.hex()
        assert len(service.eth_pendingTransactions()) == 1
        found = service.eth_getTransactionByHash(h)
        assert found["blockNumber"] is None  # pending


class TestHttpServer:
    def test_end_to_end_http(self, service):
        server = JsonRpcServer(service, port=0)
        port = server.start()
        try:
            def rpc(method, params=None, rid=1):
                body = json.dumps(
                    {"jsonrpc": "2.0", "id": rid, "method": method,
                     "params": params or []}
                ).encode()
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/", data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=5) as resp:
                    return json.loads(resp.read())

            out = rpc("eth_blockNumber")
            assert out["result"] == "0x2"
            out = rpc("eth_getBalance", ["0x" + ADDRS[1].hex(), "latest"])
            assert int(out["result"], 16) == 1005 * ETH
            out = rpc("rude_method")
            assert out["error"]["code"] == -32601
            out = rpc("eth_getBalance", ["nonsense"])
            assert "error" in out
        finally:
            server.stop()


class TestTxPool:
    def test_capacity_and_remove_mined(self):
        pool = PendingTransactionsPool(capacity=3)
        txs = [
            sign_transaction(
                Transaction(n, 1, 21000, ADDRS[1], n), KEYS[0], chain_id=1
            )
            for n in range(5)
        ]
        for t in txs:
            pool.add(t)
        assert len(pool) == 3  # oldest two evicted
        assert pool.get(txs[0].hash) is None
        assert not pool.add(txs[4])  # duplicate
        removed = pool.remove_mined([txs[3], txs[4]])
        assert removed == 2 and len(pool) == 1


class TestKeyStore:
    def test_encrypt_decrypt_roundtrip(self):
        priv = (7).to_bytes(32, "big")
        keyfile = encrypt_key(priv, "hunter2", scrypt_n=1 << 12)
        wallet = decrypt_key(keyfile, "hunter2")
        assert wallet.private_key == priv
        assert wallet.address == pubkey_to_address(privkey_to_pubkey(priv))
        with pytest.raises(KeyStoreError, match="MAC"):
            decrypt_key(keyfile, "wrong")

    def test_keystore_directory(self, tmp_path):
        ks = KeyStore(str(tmp_path))
        addr = ks.new_account("pw")
        assert ks.list_accounts() == [addr]
        wallet = ks.unlock(addr, "pw")
        assert wallet.address == addr
        with pytest.raises(KeyStoreError):
            ks.unlock(addr, "nope")
        with pytest.raises(KeyStoreError):
            ks.unlock(b"\x01" * 20, "pw")


class TestByHashAndIndexMethods:
    """The hash-keyed / index-keyed lookups and node-info methods added
    for parity with the reference's full EthService surface."""

    def test_counts_by_hash_match_by_number(self, chain, service):
        h2 = service.eth_getBlockByNumber(2)["hash"]
        assert (
            service.eth_getBlockTransactionCountByHash(h2)
            == service.eth_getBlockTransactionCountByNumber(2)
        )
        assert (
            service.eth_getUncleCountByBlockHash(h2)
            == service.eth_getUncleCountByBlockNumber(2)
        )
        missing = "0x" + "ab" * 32
        assert service.eth_getBlockTransactionCountByHash(missing) is None
        assert service.eth_getUncleCountByBlockHash(missing) is None

    def test_tx_by_block_and_index(self, chain, service):
        tx = service.eth_getTransactionByBlockNumberAndIndex(2, 0)
        assert tx is not None
        by_hash = service.eth_getTransactionByHash(tx["hash"])
        assert by_hash == tx
        h2 = service.eth_getBlockByNumber(2)["hash"]
        assert service.eth_getTransactionByBlockHashAndIndex(h2, "0x0") == tx
        assert service.eth_getTransactionByBlockNumberAndIndex(2, 7) is None

    def test_uncle_by_index_empty_blocks(self, service):
        assert service.eth_getUncleByBlockNumberAndIndex(2, 0) is None

    def test_uncle_by_index_real_ommer(self):
        import dataclasses as dc

        builder = ChainBuilder(
            Blockchain(Storages(), CFG), CFG,
            GenesisSpec(alloc={a: 1000 * ETH for a in ADDRS}),
        )
        b1 = builder.add_block([], coinbase=b"\xaa" * 20)
        ommer = dc.replace(
            b1.header, beneficiary=ADDRS[2], extra_data=b"uncle"
        )
        builder.add_block([], coinbase=b"\xaa" * 20, ommers=(ommer,))
        svc = EthService(builder.blockchain, CFG)
        u = svc.eth_getUncleByBlockNumberAndIndex(2, 0)
        assert u is not None
        assert u["hash"] == "0x" + ommer.hash.hex()
        assert u["miner"] == "0x" + ADDRS[2].hex()
        assert u["transactions"] == []
        h2 = svc.eth_getBlockByNumber(2)["hash"]
        assert svc.eth_getUncleByBlockHashAndIndex(h2, "0x0") == u
        assert svc.eth_getUncleCountByBlockHash(h2) == "0x1"

    def test_node_info_methods(self, service):
        assert service.net_listening() is True
        assert service.net_peerCount() == "0x0"
        assert service.eth_accounts() == []
        assert service.eth_mining() is False
        assert service.eth_hashrate() == "0x0"
