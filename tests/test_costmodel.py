"""Per-window cost model (observability/costmodel.py): floors,
bound classification, and the ledger x span join — driven by synthetic
ledger events and spans, no replay needed (the end-to-end surface is
covered by the bench --trace smoke)."""

import types

import pytest

from khipu_tpu.observability import recorder
from khipu_tpu.observability.costmodel import (
    DISPATCH_FLOOR_S,
    FIXED_OVERHEAD_FACTOR,
    KERNEL_HASHES_PER_S,
    TUNNEL_BYTES_PER_S,
    classify,
    cost_tracks,
    subphase_floors,
    window_costs,
)
from khipu_tpu.observability.profiler import D2H, H2D, HOST, LEDGER
from khipu_tpu.observability.trace import Tracer


@pytest.fixture(autouse=True)
def _clean_ledger():
    LEDGER.reset()
    yield
    LEDGER.disable()
    LEDGER.reset()


def _span(name, duration, **tags):
    """A snapshot-shaped span: window_costs only reads name, duration,
    and tags."""
    return types.SimpleNamespace(name=name, duration=duration, tags=tags)


class TestFloors:
    def test_no_observed_quantity_no_floor(self):
        assert subphase_floors(0, 0, 0) == {}

    def test_each_quantity_drives_its_floor(self):
        floors = subphase_floors(22_000_000, 2, 79_000_000)
        assert floors["bytes_s"] == pytest.approx(1.0)
        assert floors["dispatch_s"] == pytest.approx(
            2 * DISPATCH_FLOOR_S
        )
        assert floors["compute_s"] == pytest.approx(1.0)

    def test_partial_quantities_partial_floors(self):
        floors = subphase_floors(4096, 0, 0)
        assert set(floors) == {"bytes_s"}


class TestClassify:
    def test_bytes_bound_within_overhead_factor(self):
        floors = {"bytes_s": 0.10, "dispatch_s": 0.05}
        v = classify(0.15, floors)
        assert v["bound"] == "bytes-bound"
        assert v["attainable_s"] == pytest.approx(0.10)
        assert v["efficiency"] == pytest.approx(0.6667, abs=1e-3)

    def test_dispatch_bound_when_rtt_floor_dominates(self):
        floors = {"bytes_s": 0.01, "dispatch_s": 0.182}
        assert classify(0.2, floors)["bound"] == "dispatch-bound"

    def test_fixed_overhead_past_factor(self):
        floors = {"bytes_s": 0.01}
        v = classify(FIXED_OVERHEAD_FACTOR * 0.01 + 0.001, floors)
        assert v["bound"] == "fixed-overhead"

    def test_no_floors_is_fixed_overhead(self):
        v = classify(0.5, {})
        assert v["bound"] == "fixed-overhead"
        assert v["attainable_s"] == 0.0
        assert v["efficiency"] == 0.0

    def test_efficiency_caps_at_one(self):
        # achieved FASTER than the floor (calibration drift) reads as
        # fully efficient, never >1
        assert classify(0.05, {"bytes_s": 0.10})["efficiency"] == 1.0


def _synthetic_window():
    """One sealed window with one ledger event per sub-phase shape:
    an h2d upload, a d2h rootcheck (2 crossings), and a host-only
    pack."""
    LEDGER.enable()
    LEDGER.note_window(1, 0, 7)
    with LEDGER.context(window=1, phase="seal"):
        LEDGER.record("seal.upload", H2D, 2_200_000, duration=0.02)
        LEDGER.record("seal.pack", HOST, 4096, duration=0.01)
    with LEDGER.context(window=1, phase="collect"):
        # the collect-thread rootcheck keeps phase="collect"; its SITE
        # carries the sub-phase attribution
        LEDGER.record("seal.rootcheck", D2H, 512, duration=0.05)
        LEDGER.record("seal.rootcheck", D2H, 512, duration=0.05)


class TestWindowCosts:
    def test_not_found_shape(self):
        out = window_costs(999, spans=[])
        assert out == {
            "found": False, "number": 999,
            "ledgerEnabled": LEDGER.enabled,
        }

    def test_join_and_verdicts(self):
        _synthetic_window()
        spans = [
            # 2.2 MB / 22 MB/s = 0.1 s floor; 0.15 s achieved -> within
            # the overhead factor, bytes-bound
            _span("seal.upload", 0.15),
            # 2 d2h crossings * 91 ms = 0.182 s floor; 0.2 s achieved
            _span("seal.rootcheck", 0.20),
            # 790k hashes / 79 M/s = 10 ms floor; 0.5 s achieved is
            # >3x over it -> fixed-overhead (host-side work)
            _span("seal.pack", 0.5, nodes=790_000),
        ]
        out = window_costs(3, spans=spans)
        assert out["found"]
        assert (out["block_lo"], out["block_hi"]) == (0, 7)
        rows = out["subphases"]
        up = rows["seal.upload"]
        assert up["bound"] == "bytes-bound"
        assert up["device_bytes"] == 2_200_000
        assert up["d2h_crossings"] == 0  # h2d enqueues pay no RTT
        assert up["floors"]["bytes_s"] == pytest.approx(0.1)
        assert up["efficiency"] == pytest.approx(0.6667, abs=1e-3)
        rc = rows["seal.rootcheck"]
        assert rc["bound"] == "dispatch-bound"
        assert rc["d2h_crossings"] == 2
        pk = rows["seal.pack"]
        assert pk["bound"] == "fixed-overhead"
        assert pk["device_bytes"] == 0  # HOST bytes never cross
        assert pk["hashes"] == 790_000
        # headline: the costliest sub-phase names the verdict
        assert out["verdict"]["subphase"] == "seal.pack"
        assert out["verdict"]["bound"] == "fixed-overhead"

    def test_ledger_seconds_are_the_span_fallback(self):
        """No spans at all (tracer off while the ledger ran): achieved
        falls back to the ledger's own crossing seconds, so the RPC
        still classifies instead of reporting zeros."""
        _synthetic_window()
        out = window_costs(3, spans=[])
        assert out["subphases"]["seal.upload"]["achieved_s"] == (
            pytest.approx(0.02)
        )
        assert out["subphases"]["seal.rootcheck"]["achieved_s"] == (
            pytest.approx(0.10)
        )

    def test_cost_tracks_emit_one_counter_per_window(self):
        _synthetic_window()
        t = Tracer()
        events = cost_tracks(tracer_=t)
        assert len(events) == 1
        ev = events[0]
        assert ev["name"] == "window cost model (s)"
        assert ev["ph"] == "C"
        assert ev["args"]["achieved_s"] > 0
        assert ev["args"]["attainable_s"] > 0
        assert isinstance(ev["ts"], float)

    def test_empty_ledger_no_tracks(self):
        assert cost_tracks(tracer_=Tracer()) == []


class _FakeHist:
    def __init__(self, s):
        self.value = {"sum": s}


class TestPhaseShares:
    def test_subphases_share_the_canonical_denominator(
            self, monkeypatch):
        """Sub-phases nest inside window.seal: they are excluded from
        the denominator (no double-billing) but reported as fractions
        of the same canonical total, so seal.upload reads directly
        against a ceiling."""
        canon = recorder.LIFECYCLE_PHASES + (recorder.PHASE_STALL,)
        sums = {p: 0.0 for p in canon + recorder.SEAL_SUBPHASES
                + recorder.EXEC_SUBPHASES}
        sums[recorder.PHASE_SEAL] = 6.0
        sums[recorder.PHASE_COLLECT] = 4.0
        sums["seal.upload"] = 5.0
        monkeypatch.setattr(
            recorder, "PHASE_HISTOGRAMS",
            {p: _FakeHist(v) for p, v in sums.items()},
        )
        shares = recorder.phase_shares()
        assert shares[recorder.PHASE_SEAL] == pytest.approx(0.6)
        assert shares[recorder.PHASE_COLLECT] == pytest.approx(0.4)
        assert shares["seal.upload"] == pytest.approx(0.5)
        # zero-sum phases are omitted entirely
        assert recorder.PHASE_ANNOUNCE not in shares

    def test_empty_histograms_empty_shares(self, monkeypatch):
        canon = recorder.LIFECYCLE_PHASES + (recorder.PHASE_STALL,)
        monkeypatch.setattr(
            recorder, "PHASE_HISTOGRAMS",
            {p: _FakeHist(0.0)
             for p in canon + recorder.SEAL_SUBPHASES
             + recorder.EXEC_SUBPHASES},
        )
        assert recorder.phase_shares() == {}
