"""Batched device Keccak vs the scalar oracle (SURVEY.md §4 test plan
item 2: kernel tests — batched digests vs known-good reference)."""

import random

import numpy as np
import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.ops.keccak_jnp import keccak256_batch_jnp, pad_to_blocks
from khipu_tpu.ops.keccak import keccak256_batch


class TestJnpBatch:
    def test_small_sizes_vs_oracle(self):
        random.seed(7)
        # one- and two-block classes (keeps CPU compile time sane)
        msgs = [random.randbytes(n) for n in (0, 1, 31, 55, 56, 135, 136, 200, 271)]
        got = keccak256_batch_jnp(msgs)
        for g, m in zip(got, msgs):
            assert g == keccak256(m), f"len={len(m)}"

    def test_batch_order_preserved_across_buckets(self):
        random.seed(8)
        msgs = [random.randbytes(n) for n in (140, 3, 139, 7, 0)]
        got = keccak256_batch_jnp(msgs)
        assert [g for g in got] == [keccak256(m) for m in msgs]

    def test_empty_batch(self):
        assert keccak256_batch_jnp([]) == []

    def test_wrong_class_rejected(self):
        with pytest.raises(ValueError):
            pad_to_blocks([b"x" * 200], 1)

    def test_dispatcher_jnp_on_cpu(self):
        msgs = [b"khipu", b""]
        assert keccak256_batch(msgs, impl="auto") == [keccak256(m) for m in msgs]


class TestPallasInterpret:
    """Interpret-mode emulation of the kernel: minutes per tile on CPU,
    so marked slow (run with `pytest -m slow`). The round permutation
    itself (_round/_RC32) is fast-tested through the jnp path above,
    which the Pallas kernel shares verbatim."""

    @pytest.mark.slow
    def test_one_block_class_vs_oracle(self):
        from khipu_tpu.ops.keccak_pallas import keccak256_batch_pallas

        random.seed(9)
        msgs = [random.randbytes(n) for n in (0, 1, 64, 135)]
        got = keccak256_batch_pallas(msgs, interpret=True)
        for g, m in zip(got, msgs):
            assert g == keccak256(m), f"len={len(m)}"

    @pytest.mark.slow
    def test_fixed_path_vs_oracle(self):
        from khipu_tpu.ops.keccak_pallas import keccak256_fixed

        rng = np.random.default_rng(3)
        data = rng.integers(0, 256, size=(6, 100), dtype=np.uint8)
        out = keccak256_fixed(data, interpret=True)
        assert out.shape == (6, 32)
        for i in range(6):
            assert out[i].tobytes() == keccak256(data[i].tobytes())


class TestPallasLayout:
    """Numpy-only checks of the Pallas host-side layout logic (retile and
    its inverse indexing) — the kernel-independent part that interpret
    mode would otherwise be the only off-TPU coverage for."""

    def test_retile_roundtrip_indexing(self):
        from khipu_tpu.ops.keccak_pallas import TILE, retile

        rng = np.random.default_rng(11)
        nblocks, batch = 2, 2 * TILE
        blocks = rng.integers(0, 2**32, size=(nblocks, 34, batch), dtype=np.uint64
                              ).astype(np.uint32)
        tiled = retile(blocks)
        assert tiled.shape == (batch // TILE, nblocks * 34, 8, 128)
        # message j's word w must land at [j // TILE, w, (j % TILE) // 128,
        # j % 128] — the exact inverse used by keccak256_batch_pallas.
        for j in (0, 1, 127, 128, 1023, 1024, 2047):
            t, r = divmod(j, TILE)
            s, l = divmod(r, 128)
            np.testing.assert_array_equal(
                tiled[t, :, s, l],
                blocks.reshape(nblocks * 34, batch)[:, j],
            )
