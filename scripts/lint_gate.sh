#!/usr/bin/env bash
# Static-analysis gate: khipu-lint self-scan of the khipu_tpu tree.
# Non-zero exit on any finding that is neither pragma-annotated
# (# khipu-lint: ok KL00x <reason>) nor in the committed baseline
# (khipu_tpu/analysis/baseline.json) — the invariants it checks are
# the ones no runtime test can see being absent: TransferLedger
# coverage of device crossings (KL001), chaos fail-stop safety
# (KL002), replay determinism (KL003), lock order (KL004),
# observability discipline (KL005), mutable defaults (KL006).
# docs/static_analysis.md has the catalog.
#
# Usage:
#   scripts/lint_gate.sh [paths...] [--format=json] [...]
#   scripts/lint_gate.sh --annotate [paths...]
#
# --annotate is the review-tooling mode: findings print as
# 'file:line: [KL00x] msg' lines and the SARIF-ish JSON document lands
# at $KHIPU_LINT_ARTIFACT (default /tmp/khipu_lint_findings.json).
#
# Pure stdlib — no jax import, runs in milliseconds anywhere.
set -euo pipefail

cd "$(dirname "$0")/.."

args=()
for a in "$@"; do
  if [ "$a" = "--annotate" ]; then
    args+=(--annotate "${KHIPU_LINT_ARTIFACT:-/tmp/khipu_lint_findings.json}")
  else
    args+=("$a")
  fi
done
if [ ${#args[@]} -eq 0 ]; then
  args=(khipu_tpu)
fi

python -m khipu_tpu.analysis ${args[@]+"${args[@]}"}
