#!/usr/bin/env bash
# Bench regression gate: tier-1 tests + bench.py --compare against a
# captured baseline. Non-zero exit on a test failure OR a bench
# regression past the thresholds — the one command CI (or a human
# about to merge) runs to know the change neither broke correctness
# nor quietly regressed the headline replay configs.
#
# Usage:
#   scripts/bench_gate.sh [BASELINE.json] [extra bench.py args...]
#
# Defaults: BENCH_r10.json (the newest captured baseline — first one
# with the kesque engine, so every replay line carries
# persist_bytes_per_sec and the capture includes the three gated
# ingest metrics). NOTE r10 was captured on a DIFFERENT (slower) host
# than r09 — an A/B of pre-/post-kesque code on the r10 host showed
# the r09-era code at 0.50-0.78x of the r09 figures while the kesque
# branch beat it on every fixture, so the r09->r10 headline drop
# (62.52 -> 32.84 parallel) is host variance, not a regression.
# Ratios are only meaningful against a same-host baseline, which is
# exactly what re-baselining restores. Thresholds, with two overrides:
#   * bytes ratio pinned at 1.05x (r10 was captured by the same
#     sub-phase-instrumented code the gate runs — device bytes/block
#     should reproduce within noise, not the legacy 1.25x slack);
#   * blocks ratio WIDENED 0.8 -> 0.65: measured same-code spreads on
#     the r10 host are parallel 32.8-49.8, mixed-contract 49.2-75.1,
#     conflict-storm 119.8-164.5 b/s (clean, idle, identical tree) —
#     a 0.8 gate flakes on that noise floor. 0.65 still catches any
#     2x regression; tighten back when captures move to a host with a
#     tighter noise floor (take best-of-N there first).
# Override per-run:
#   scripts/bench_gate.sh BENCH_r07.json --min-blocks-ratio=0.5
# (a later arg wins: bench.py takes the last value of a repeated flag)
set -euo pipefail

cd "$(dirname "$0")/.."

BASELINE="${1:-BENCH_r10.json}"
shift || true

if [ ! -f "$BASELINE" ]; then
    echo "bench_gate: baseline '$BASELINE' not found" >&2
    exit 2
fi

echo "== khipu-lint static analysis =="
scripts/lint_gate.sh

echo "== tier-1 tests =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== rebalance smoke (a wedged cutover fails the gate) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --rebalance --smoke

echo "== reorg smoke (a torn switch, torn read, or missing khipu_reorg_* family fails the gate) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --reorg --smoke

echo "== ingest smoke (segment ingest < 3x the per-node walk, read amp >= 1.5x, or a missing khipu_kesque_* family fails the gate) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --ingest --smoke

echo "== bench regression gate (baseline: $BASELINE) =="
# --diff: on a failure (or any movement past tolerance) print the
# differential attribution — WHICH phase/sub-phase site moved and by
# how many bytes/block — instead of just the tripped headline ratio
JAX_PLATFORMS="${JAX_PLATFORMS:-}" python bench.py \
    --compare="$BASELINE" --diff --max-bytes-ratio=1.05 \
    --min-blocks-ratio=0.65 "$@"

echo "bench_gate: OK"
