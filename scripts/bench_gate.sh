#!/usr/bin/env bash
# Bench regression gate: tier-1 tests + bench.py --compare against a
# captured baseline. Non-zero exit on a test failure OR a bench
# regression past the thresholds — the one command CI (or a human
# about to merge) runs to know the change neither broke correctness
# nor quietly regressed the headline replay configs.
#
# Usage:
#   scripts/bench_gate.sh [BASELINE.json] [extra bench.py args...]
#
# Defaults: BENCH_r11.json (the newest captured baseline — first one
# carrying a host_speed_score line, so --compare normalizes every
# blocks/s ratio by the keccak-microworkload score ratio of the
# capture host vs the gate host). Thresholds, with two overrides:
#   * bytes ratio pinned at 1.05x (r10+ captures come from the same
#     sub-phase-instrumented code the gate runs — device bytes/block
#     should reproduce within noise, not the legacy 1.25x slack);
#   * blocks ratio RE-TIGHTENED 0.65 -> 0.8: the 0.65 widening existed
#     because r10 was captured on a different (slower) host than r09
#     and raw cross-host ratios flake — the r09->r10 "drop" (62.52 ->
#     32.84 parallel) was pure host variance. The host_speed_score
#     normalization now divides that variance out (adjusted = measured
#     * score_base/score_now), so the residual spread the ratio judges
#     is scheduler/code noise, which 0.8 clears. Baselines without a
#     score (r10 and older) still compare raw — pass an explicit
#     --min-blocks-ratio=0.65 when gating against one of those.
# Override per-run:
#   scripts/bench_gate.sh BENCH_r07.json --min-blocks-ratio=0.5
# (a later arg wins: bench.py takes the last value of a repeated flag)
set -euo pipefail

cd "$(dirname "$0")/.."

BASELINE="${1:-BENCH_r11.json}"
shift || true

if [ ! -f "$BASELINE" ]; then
    echo "bench_gate: baseline '$BASELINE' not found" >&2
    exit 2
fi

echo "== khipu-lint static analysis =="
scripts/lint_gate.sh

echo "== tier-1 tests =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== rebalance smoke (a wedged cutover fails the gate) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --rebalance --smoke

echo "== reorg smoke (a torn switch, torn read, or missing khipu_reorg_* family fails the gate) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --reorg --smoke

echo "== ingest smoke (segment ingest < 3x the per-node walk, read amp >= 1.5x, or a missing khipu_kesque_* family fails the gate) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --ingest --smoke

echo "== conformance corpus (any failing GeneralStateTest case — statetest_pass_rate < 1.0 — fails the gate) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --conformance

echo "== tx passport smoke (missing ingress->durable / ingress->replica-visible p99, <99% complete journeys, no retraction-crossing or vector-lane journey, or a khipu_tx_* family rendered more than once-per-TYPE fails the gate) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --serve --smoke

echo "== fleet serve smoke (a stale read under a consistent-read token, an unmirrored reorg, or a missing khipu_fleet_* family fails the gate) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --serve --http --smoke

echo "== gameday smoke (the composed failure timeline: any RYW/retraction/token-floor/epoch/roots invariant, a missing khipu_gameday_* family, or an unlabeled watchdog trip fails the gate) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --gameday --smoke

echo "== bench regression gate (baseline: $BASELINE) =="
# --diff: on a failure (or any movement past tolerance) print the
# differential attribution — WHICH phase/sub-phase site moved and by
# how many bytes/block — instead of just the tripped headline ratio
JAX_PLATFORMS="${JAX_PLATFORMS:-}" python bench.py \
    --compare="$BASELINE" --diff --max-bytes-ratio=1.05 \
    --min-blocks-ratio=0.8 "$@"

echo "bench_gate: OK"
