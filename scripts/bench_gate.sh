#!/usr/bin/env bash
# Bench regression gate: tier-1 tests + bench.py --compare against a
# captured baseline. Non-zero exit on a test failure OR a bench
# regression past the thresholds — the one command CI (or a human
# about to merge) runs to know the change neither broke correctness
# nor quietly regressed the headline replay configs.
#
# Usage:
#   scripts/bench_gate.sh [BASELINE.json] [extra bench.py args...]
#
# Defaults: BENCH_r05.json (the newest captured baseline) and the
# default thresholds baked into bench.py (blocks/s may drop to 0.5x,
# collect share may grow +0.15 absolute, device bytes/block may grow
# 1.25x — see DEFAULT_COMPARE_THRESHOLDS). Override per-run, e.g.:
#   scripts/bench_gate.sh BENCH_r05.json --min-blocks-ratio=0.8
set -euo pipefail

cd "$(dirname "$0")/.."

BASELINE="${1:-BENCH_r05.json}"
shift || true

if [ ! -f "$BASELINE" ]; then
    echo "bench_gate: baseline '$BASELINE' not found" >&2
    exit 2
fi

echo "== khipu-lint static analysis =="
scripts/lint_gate.sh

echo "== tier-1 tests =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== bench regression gate (baseline: $BASELINE) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-}" python bench.py \
    --compare="$BASELINE" "$@"

echo "bench_gate: OK"
