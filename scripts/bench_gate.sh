#!/usr/bin/env bash
# Bench regression gate: tier-1 tests + bench.py --compare against a
# captured baseline. Non-zero exit on a test failure OR a bench
# regression past the thresholds — the one command CI (or a human
# about to merge) runs to know the change neither broke correctness
# nor quietly regressed the headline replay configs.
#
# Usage:
#   scripts/bench_gate.sh [BASELINE.json] [extra bench.py args...]
#
# Defaults: BENCH_r09.json (the newest captured baseline — the first
# one captured with the conflict-aware scheduler + vectorized fast
# path + pipelined sender recovery, so its blocks/s carries the
# demolished execute wall: 62.52 b/s parallel vs r08's 30.84, and it
# adds the conflict-storm + mixed-contract fixtures) and the
# thresholds baked into bench.py, with two overrides:
#   * bytes ratio pinned at 1.05x (r09 was captured by the same
#     sub-phase-instrumented code the gate runs — device bytes/block
#     should reproduce within noise, not the legacy 1.25x slack);
#   * blocks ratio kept TIGHT at 0.8 (r09 beats r08 on both
#     pre-existing fixtures, so the post-seal-wall variance argument
#     still holds; a 0.5 gate would wave through a 2x regression).
# Override per-run:
#   scripts/bench_gate.sh BENCH_r07.json --min-blocks-ratio=0.5
# (a later arg wins: bench.py takes the last value of a repeated flag)
set -euo pipefail

cd "$(dirname "$0")/.."

BASELINE="${1:-BENCH_r09.json}"
shift || true

if [ ! -f "$BASELINE" ]; then
    echo "bench_gate: baseline '$BASELINE' not found" >&2
    exit 2
fi

echo "== khipu-lint static analysis =="
scripts/lint_gate.sh

echo "== tier-1 tests =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== rebalance smoke (a wedged cutover fails the gate) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --rebalance --smoke

echo "== reorg smoke (a torn switch, torn read, or missing khipu_reorg_* family fails the gate) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --reorg --smoke

echo "== bench regression gate (baseline: $BASELINE) =="
# --diff: on a failure (or any movement past tolerance) print the
# differential attribution — WHICH phase/sub-phase site moved and by
# how many bytes/block — instead of just the tripped headline ratio
JAX_PLATFORMS="${JAX_PLATFORMS:-}" python bench.py \
    --compare="$BASELINE" --diff --max-bytes-ratio=1.05 \
    --min-blocks-ratio=0.8 "$@"

echo "bench_gate: OK"
